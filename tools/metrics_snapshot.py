#!/usr/bin/env python
"""Dump the paddle_tpu observability registry — or selfcheck it.

Two jobs:

* ``python tools/metrics_snapshot.py [--format prometheus|json|chrome]``
  prints the current process-wide registry. Mostly useful embedded
  (``from tools.metrics_snapshot import dump``) or from a debugger/REPL
  at the end of a serving/training run — a fresh process has an empty
  registry.
* ``python tools/metrics_snapshot.py --selfcheck`` exercises the whole
  metrics core — registry, concurrency, histogram bucket edges, all
  three exporters (incl. the 0.0.4 help-vs-label escaping split) —
  plus the tracing span ring (wraparound, concurrent recording, the
  tracer arg guard), the flight-recorder dump schema (write -> stdlib
  json load -> ``tracing.load_dump`` validation -> ``request_summary``
  replay) and retention manifest, the windowed time-series ring
  (rate / delta-quantile / gauge stats on a synthetic clock), the
  SLO engine (burn-rate breach -> counter + ``validate_report`` schema
  + ``slo_burn_rate`` dump), the cost catalog (record -> program_*
  gauge sections -> derived intensity/MFU/roofline against a synthetic
  dispatch histogram), the memory layer (synthetic census ->
  live_array gauges; MemoryMonitor headroom breach -> ``hbm_pressure``
  dump schema), the resilience telemetry (preemption/cancel/shed
  counter families; ``preemption`` and ``operator_abort`` dump schemas
  with their request_summary digests), and the training health layer
  (ISSUE 14: telemetry-spec grouping/packing, the train_group_* gauge
  families under their bounded label sets, the TrainHealthMonitor
  detector matrix on a synthetic clock with all four dump reasons —
  ``non_finite_loss`` / ``grad_norm_spike`` / ``loss_divergence`` /
  ``data_stall`` — loadable with their ``breach_summary`` digests, and
  the instrumented-loader surfaces), and exits non-zero on any
  violation.
  Wired into tools/lint.sh so the tier-0 gate
  (tests/test_graftlint_gate.py) catches a broken metrics/tracing/SLO
  subsystem before any test imports jax.

The selfcheck must run in a bare container: paddle_tpu/__init__ imports
jax, so when the package isn't already loaded we load
paddle_tpu/observability STANDALONE by path (it is stdlib-only by
contract — that load failing IS a selfcheck failure).
"""
import argparse
import importlib
import importlib.util
import json
import os
import shutil
import sys
import tempfile
import threading
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_observability():
    """The already-imported package when present; otherwise a standalone
    by-path load that never touches paddle_tpu/__init__ (no jax)."""
    mod = sys.modules.get("paddle_tpu.observability")
    if mod is not None:
        return mod
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "observability")
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.observability", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.observability"] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_serving():
    """paddle_tpu.serving, stdlib-only: when the real package is not
    loaded, a NAMESPACE stub stands in for `paddle_tpu` (its __init__
    imports jax, which a bare container lacks) so the serving package's
    relative imports resolve against the standalone observability load
    above. The serving package importing without jax/numpy IS part of
    the contract under test."""
    mod = sys.modules.get("paddle_tpu.serving")
    if mod is not None:
        return mod
    if "paddle_tpu" not in sys.modules:
        stub = types.ModuleType("paddle_tpu")
        stub.__path__ = [os.path.join(REPO_ROOT, "paddle_tpu")]
        sys.modules["paddle_tpu"] = stub
    _load_observability()
    return importlib.import_module("paddle_tpu.serving")


def dump(fmt="json", registry=None, obs=None):
    """Render the registry in one of the three exporter formats."""
    obs = obs or _load_observability()
    registry = registry or obs.get_registry()
    if fmt == "prometheus":
        return obs.to_prometheus(registry)
    if fmt == "json":
        return obs.to_json(registry, indent=1)
    if fmt == "chrome":
        return json.dumps({"traceEvents":
                           obs.chrome_counter_events(registry)}, indent=1)
    raise ValueError(f"unknown format {fmt!r}")


def selfcheck():
    """Exercise the metrics core; returns a list of failure strings."""
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    try:
        obs = _load_observability()
    except Exception as e:
        return [f"standalone (pre-jax) observability import failed: {e}"]

    reg = obs.MetricsRegistry()    # private registry: no global pollution

    # counters: monotonic, concurrent-exact
    c = reg.counter("sc_requests_total", help="selfcheck")
    threads = [threading.Thread(
        target=lambda: [c.inc() for _ in range(1000)]) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(c.value == 8000, f"concurrent counter lost updates: {c.value}")
    try:
        c.inc(-1)
        check(False, "negative counter increment not rejected")
    except ValueError:
        pass

    # gauges: set/inc/dec/set_max, labels
    g = reg.gauge("sc_depth", labels=("queue",))
    g.labels(queue="a").set(3)
    g.labels(queue="a").inc(2)
    g.labels(queue="a").dec()
    check(g.labels(queue="a").value == 4.0,
          f"gauge arithmetic wrong: {g.labels(queue='a').value}")
    g.labels(queue="a").set_max(2)
    check(g.labels(queue="a").value == 4.0, "set_max lowered the gauge")

    # histograms: inclusive `le` edges, count/sum, quantiles
    h = reg.histogram("sc_latency_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    child = h.labels()
    check(child.bucket_counts == [1, 2, 1, 1],
          f"bucket edges not inclusive-upper: {child.bucket_counts}")
    check(child.count == 5 and abs(child.sum - 106.6) < 1e-9,
          f"count/sum wrong: {child.count}/{child.sum}")
    q50 = h.quantile(0.5)
    check(q50 is not None and 0.1 <= q50 <= 1.0,
          f"median {q50} outside its bucket")
    check(reg.histogram("sc_latency_seconds") is h,
          "histogram get-or-create returned a different family")
    try:
        reg.counter("sc_latency_seconds")
        check(False, "kind conflict not rejected")
    except ValueError:
        pass

    # tracer guard: non-scalars must be rejected loudly
    try:
        reg.counter("sc_bad_total").inc(object())
        check(False, "non-scalar record not rejected")
    except TypeError:
        pass

    # exporters
    prom = obs.to_prometheus(reg)
    for needle in ("# TYPE sc_requests_total counter",
                   "# TYPE sc_depth gauge",
                   "# TYPE sc_latency_seconds histogram",
                   'sc_latency_seconds_bucket{le="+Inf"} 5',
                   'sc_depth{queue="a"} 4'):
        check(needle in prom, f"prometheus output missing {needle!r}")
    # exposition 0.0.4 escaping SPLIT: help text escapes only \ and
    # newline (quotes stay raw — help is unquoted); label VALUES escape
    # the quote too (they sit inside quotes)
    reg.counter('sc_esc_total', help='say "hi"\nback\\slash',
                labels=("q",)).labels(q='a"b\\c').inc()
    prom = obs.to_prometheus(reg)
    check('# HELP sc_esc_total say "hi"\\nback\\\\slash' in prom,
          "help escaping wrong (quotes must stay raw, \\n/\\\\ escape): "
          + [l for l in prom.splitlines()
             if l.startswith("# HELP sc_esc_total")][0])
    check('sc_esc_total{q="a\\"b\\\\c"} 1' in prom,
          "label-value escaping wrong: "
          + [l for l in prom.splitlines()
             if l.startswith("sc_esc_total{")][0])
    snap = json.loads(obs.to_json(reg))
    check(set(snap) == {"time", "metrics"}, "json envelope wrong")
    check(snap["metrics"]["sc_requests_total"]["children"][""]["value"]
          == 8000, "json snapshot value wrong")
    ev = obs.chrome_counter_events(reg, pid=1)
    check(len(ev) > 0, "no chrome counter samples recorded")
    check(all(e["ph"] == "C" and {"name", "ts", "dur", "pid", "tid",
                                  "args"} <= set(e) for e in ev),
          "chrome counter events malformed")

    # span recorder: bounded ring, wraparound, concurrent recording
    tr = obs.tracing.SpanRecorder(capacity=32)
    for i in range(50):
        tr.event("warm", request=0, i=i)
    check(len(tr) == 32 and tr.recorded_total == 50,
          f"ring wraparound wrong: len={len(tr)} "
          f"recorded={tr.recorded_total}")
    threads = [threading.Thread(
        target=lambda: [tr.event("t", request=1) for _ in range(500)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(len(tr) == 32 and tr.recorded_total == 50 + 2000,
          f"concurrent span recording lost appends: "
          f"{tr.recorded_total}")
    # recorded AFTER the storm so it survives the bounded ring into
    # the flight dump below
    with tr.span("prefill_chunk", request=7, width=4, granted=4):
        pass
    got = tr.spans(request=7)
    check(len(got) == 1 and got[0]["name"] == "prefill_chunk"
          and got[0]["args"]["width"] == 4 and got[0]["dur_us"] >= 0,
          f"span record malformed: {got}")
    try:
        tr.event("bad", v=object())
        check(False, "span arg guard let a non-scalar through")
    except TypeError:
        pass
    sev = obs.tracing.chrome_span_events(tr, pid=1)
    check(any(e["ph"] == "X" for e in sev)
          and any(e["ph"] == "M" for e in sev),
          "chrome span events missing X spans or M lane names")
    check(all({"name", "ph", "ts", "dur", "pid", "tid", "args"}
              <= set(e) for e in sev), "chrome span events malformed")

    # flight-recorder dump: write, stdlib-load, schema-validate
    fr = obs.tracing.FlightRecorder(recorder=tr)
    check(fr.trigger("sc_anomaly") is None,
          "disarmed flight recorder wrote a dump")
    d = tempfile.mkdtemp(prefix="sc_flightrec_")
    try:
        fr.arm(d, window_s=60.0)
        path = fr.trigger("sc_anomaly", request=7, step=3)
        check(path is not None and os.path.exists(path),
              "armed flight recorder wrote nothing")
        check(fr.trigger("sc_anomaly") is None,
              "per-reason cooldown did not rate-limit")
        dump = obs.tracing.load_dump(path)      # schema validation
        check(dump["reason"] == "sc_anomaly" and 7 in dump["requests"],
              f"dump content wrong: reason={dump['reason']} "
              f"requests={dump['requests']}")
        check(dump["context"].get("step") == 3,
              f"dump context lost: {dump['context']}")
        check(len(dump["spans"]) == len(tr),
              f"dump spans {len(dump['spans'])} != ring {len(tr)}")
        check(isinstance(dump["metrics"], dict),
              "dump metrics snapshot missing")
        digest = obs.tracing.request_summary(7, spans=dump["spans"])
        check(digest["prefill_chunks"] == [{"granted": 4,
                                            "requested": None}],
              f"request_summary from dump wrong: {digest}")
        # a truncated/foreign file must be REJECTED, not half-parsed
        bad = os.path.join(d, "not_a_dump.json")
        with open(bad, "w") as f:
            json.dump({"schema": "something/else"}, f)
        try:
            obs.tracing.load_dump(bad)
            check(False, "load_dump accepted a foreign schema")
        except ValueError:
            pass
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # timeseries ring: windowed rate / delta-quantile / gauge stats on
    # a synthetic clock (explicit now= — determinism is the contract)
    reg2 = obs.MetricsRegistry()
    ts = obs.TimeSeries(registry=reg2, capacity=8)
    c2 = reg2.counter("ts_total")
    h2 = reg2.histogram("ts_seconds", buckets=(0.1, 1.0, 10.0))
    g2 = reg2.gauge("ts_depth")
    c2.inc(0); g2.set(0)            # create children before sampling
    h2.observe(0.05)
    ts.sample(now=0.0)
    c2.inc(50)
    for v in (0.5, 0.5, 5.0):
        h2.observe(v)
    g2.set(4)
    ts.sample(now=10.0)
    check(ts.rate("ts_total", 10.0, now=10.0) == 5.0,
          f"windowed counter rate wrong: "
          f"{ts.rate('ts_total', 10.0, now=10.0)}")
    q = ts.quantile("ts_seconds", 0.5, 10.0, now=10.0)
    check(q is not None and 0.1 < q <= 1.0,
          f"delta-histogram median {q} outside its bucket (the 0.05 "
          "observed BEFORE the window must not count)")
    check(ts.count("ts_seconds", 10.0, now=10.0) == 3,
          "windowed observation count wrong")
    frac = ts.fraction_over("ts_seconds", 1.0, 10.0, now=10.0)
    check(frac is not None and abs(frac - 1 / 3) < 1e-9,
          f"fraction_over wrong: {frac} != 1/3")
    st = ts.gauge_stats("ts_depth", 20.0, now=10.0)
    check(st == {"min": 0.0, "max": 4.0, "mean": 2.0, "last": 4.0,
                 "samples": 2}, f"gauge stats wrong: {st}")
    for i in range(20):             # bounded ring: drops are counted
        ts.sample(now=20.0 + i)
    check(len(ts.ring("ts_total")) == 8 and ts.dropped > 0,
          f"timeseries ring not bounded: len="
          f"{len(ts.ring('ts_total'))} dropped={ts.dropped}")

    # registry timeline ring: overflow must be visible, not silent
    reg3 = obs.MetricsRegistry(timeline_capacity=4)
    g3 = reg3.gauge("tl_depth")
    for i in range(10):
        g3.set(i)
    tstats = reg3.timeline_stats()
    check(tstats == {"samples": 4, "capacity": 4, "dropped": 6},
          f"timeline drop accounting wrong: {tstats}")
    check(reg3.snapshot().get("_timeline", {}).get("dropped") == 6,
          "snapshot() does not carry the timeline drop count")

    # SLO engine: synthetic breach -> counter + schema + burn-rate
    # flight dump with retention manifest
    reg4 = obs.MetricsRegistry()
    ts4 = obs.TimeSeries(registry=reg4)
    lat = reg4.histogram("slo_ttft_seconds", buckets=(0.01, 0.1, 1.0))
    lat.observe(0.005)
    ts4.sample(now=0.0)
    for _ in range(10):
        lat.observe(0.5)            # 100% of the window over a 0.1 SLO
    ts4.sample(now=5.0)
    ring4 = obs.tracing.SpanRecorder()
    fr4 = obs.tracing.FlightRecorder(recorder=ring4, min_interval_s=0.0)
    eng = obs.SLOEngine(
        [{"name": "ttft_p99", "kind": "quantile",
          "metric": "slo_ttft_seconds", "q": 0.99, "max": 0.1}],
        windows=[{"name": "fast", "window_s": 10.0,
                  "burn_threshold": 14.0}],
        timeseries=ts4, registry=reg4, recorder=ring4,
        flight_recorder=fr4)
    d4 = tempfile.mkdtemp(prefix="sc_slo_")
    try:
        fr4.arm(d4, max_dumps=2)
        rep = eng.evaluate(now=5.0)
        obs.validate_report(rep)    # schema contract
        check(rep["breaches"] == 1 and eng.breaches_total == 1,
              f"synthetic cliff did not breach: {rep['breaches']}")
        ev = rep["objectives"][0]["windows"]["fast"]
        check(ev["breached"] and ev["burn_rate"] >= 14.0,
              f"burn rate wrong: {ev}")
        snap4 = reg4.snapshot()
        bc = snap4.get("slo_breaches_total", {}).get("children", {})
        check(sum(ch["value"] for ch in bc.values()) == 1,
              f"slo_breaches_total not counted: {bc}")
        dumps4 = [f for f in os.listdir(d4)
                  if f.startswith("flightrec_slo_burn_rate")]
        check(len(dumps4) == 1, f"no slo_burn_rate dump: {dumps4}")
        man = obs.tracing.load_manifest(d4)
        check([e["file"] for e in man["dumps"]] == dumps4
              and man["dumps"][0]["reason"] == "slo_burn_rate",
              f"retention manifest wrong: {man}")
        # a healthy stream must NOT breach: the cliff era ends at t=5;
        # by t=16 the 10s window holds only healthy observations
        lat2 = reg4.histogram("slo_ttft_seconds")
        ts4.sample(now=6.0)
        for _ in range(10):
            lat2.observe(0.005)
        ts4.sample(now=16.0)
        rep2 = eng.evaluate(now=16.0)
        check(eng.breaches_total == 1,
              f"healthy window breached: {rep2['breaches']}")
        ev2 = rep2["objectives"][0]["windows"]["fast"]
        check(ev2 is not None and ev2["burn_rate"] == 0.0
              and not ev2["breached"],
              f"healthy burn rate not zero: {ev2}")
        try:
            obs.validate_report({"schema": "something/else"})
            check(False, "validate_report accepted a foreign schema")
        except ValueError:
            pass
    finally:
        shutil.rmtree(d4, ignore_errors=True)

    # cost catalog: record -> program_* gauges -> derived MFU/roofline
    # against a synthetic dispatch histogram (all host numbers — the
    # jax-artifact analyses are exercised by the train_obs gate)
    reg5 = obs.MetricsRegistry()
    cat = obs.CostCatalog(registry=reg5)
    e = cat.record("sc_step", flops=2e9, bytes_accessed=1e9,
                   arg_bytes=6e8, out_bytes=1e8, temp_bytes=3e8,
                   signature="s0")
    check(e["intensity"] == 2.0 and e["peak_hbm"] == 1e9,
          f"catalog intensity/peak wrong: {e}")
    snap5 = reg5.snapshot()
    for fam in ("program_flops", "program_bytes",
                "program_peak_hbm_bytes", "program_arithmetic_intensity"):
        v = snap5.get(fam, {}).get("children", {}).get("sc_step",
                                                       {}).get("value")
        check(v is not None and v > 0,
              f"catalog gauge {fam} missing from the snapshot: {v}")
    h5 = reg5.histogram("dispatch_seconds", labels=("program",))
    h5.labels(program="sc_step").observe(0.01)
    derived = cat.derive(registry=reg5, peak_flops_override=1e12,
                         peak_bw_override=1e11)
    row = derived.get("sc_step")
    check(row is not None and row["mfu"] is not None
          and 0 < row["mfu"] <= 1.0,
          f"derived MFU wrong: {row}")
    # intensity 2.0 * bw 1e11 = 2e11 attainable < 1e12 peak: the
    # program is bandwidth-bound, so roofline_frac > mfu
    check(row["roofline_frac"] > row["mfu"],
          f"roofline did not clamp to bandwidth: {row}")
    check(reg5.snapshot()["program_mfu"]["children"]["sc_step"]["value"]
          == row["mfu"], "program_mfu gauge not set")
    # re-analysis updates, second signature recorded
    cat.record("sc_step", flops=4e9, bytes_accessed=1e9, signature="s1")
    ent = cat.entries()["sc_step"]
    check(ent["analyses"] == 2 and len(ent["signatures"]) == 2,
          f"catalog signature history wrong: {ent}")
    check(len(cat.table()) == 1 and cat.table()[0]["signatures"] == 2,
          "catalog table wrong")

    # memory layer: synthetic census -> gauges; monitor breach ->
    # hbm_pressure dump with a validated schema + context
    reg6 = obs.MetricsRegistry()
    census = {"kv_cache": {"count": 4, "bytes": 4096},
              "float32[8, 8]": {"count": 2, "bytes": 512}}
    obs.record_census(census, registry=reg6)
    snap6 = reg6.snapshot()
    check(snap6["live_arrays"]["children"]["kv_cache"]["value"] == 4
          and snap6["live_array_bytes_total"]["children"][""]["value"]
          == 4608, f"census gauges wrong")
    check(obs.census_diff(census, census) == {},
          "identical censuses diffed nonempty")
    diff = obs.census_diff(census, {"kv_cache": {"count": 5,
                                                 "bytes": 5120}})
    check(diff == {"kv_cache": {"count": 1, "bytes": 1024},
                   "float32[8, 8]": {"count": -2, "bytes": -512}},
          f"census diff wrong: {diff}")
    ring6 = obs.tracing.SpanRecorder()
    fr6 = obs.tracing.FlightRecorder(recorder=ring6, min_interval_s=0.0)
    try:
        obs.MemoryMonitor(min_headroom_frac=1.5)
        check(False, "min_headroom_frac >= 1 not rejected")
    except ValueError:
        pass
    mon = obs.MemoryMonitor(budget_bytes=1000.0, min_headroom_frac=0.2,
                            registry=reg6, flight_recorder=fr6)
    rep = mon.update(in_use_bytes=500.0)
    check(rep["pressure"] is False and rep["headroom_frac"] == 0.5,
          f"healthy headroom misreported: {rep}")
    d6 = tempfile.mkdtemp(prefix="sc_hbm_")
    try:
        fr6.arm(d6, window_s=60.0)
        rep = mon.update(in_use_bytes=950.0)
        check(rep["pressure"] is True and mon.pressure_events == 1,
              f"pressure not detected: {rep}")
        dumps = [f for f in os.listdir(d6)
                 if f.startswith("flightrec_hbm_pressure")]
        check(len(dumps) == 1, f"no hbm_pressure dump: {dumps}")
        if dumps:
            dump = obs.tracing.load_dump(os.path.join(d6, dumps[0]))
            check(dump["reason"] == "hbm_pressure"
                  and dump["context"].get("in_use_bytes") == 950
                  and dump["context"].get("budget_bytes") == 1000
                  and dump["context"].get("min_headroom_frac") == 0.2,
                  f"hbm_pressure dump context wrong: {dump['context']}")
        g6 = reg6.snapshot()
        check(g6["hbm_bytes_in_use"]["children"][""]["value"] == 950.0
              and g6["hbm_bytes_high_water"]["children"][""]["value"]
              == 950.0
              and abs(g6["hbm_headroom_frac"]["children"][""]["value"]
                      - 0.05) < 1e-9,
              "hbm gauges wrong after pressure update")
    finally:
        shutil.rmtree(d6, ignore_errors=True)

    # resilience telemetry (ISSUE 11): the preemption/cancel/shed
    # counter families, and the `preemption` / `operator_abort` dump
    # schemas with their request_summary digests — all stdlib-only
    reg7 = obs.MetricsRegistry()
    pre = reg7.counter("serve_preemptions_total", labels=("reason",))
    pre.labels(reason="kv_alloc").inc()
    pre.labels(reason="admission").inc(2)
    reg7.counter("serve_requests_cancelled_total").inc()
    reg7.counter("serve_requests_shed_total",
                 labels=("reason",)).labels(reason="slo_burn").inc()
    reg7.counter("serve_requests_failed_total",
                 labels=("reason",)).labels(
                     reason="kv_alloc_failure").inc()
    snap7 = reg7.snapshot()
    ch = snap7["serve_preemptions_total"]["children"]
    check(sum(c["value"] for c in ch.values()) == 3 and len(ch) == 2,
          f"preemption counter children wrong: {ch}")
    prom7 = obs.to_prometheus(reg7)
    check('serve_preemptions_total{reason="admission"} 2' in prom7,
          "preemption counter missing from exposition")
    ring7 = obs.tracing.SpanRecorder()
    ring7.event("submit", request="pr1", prompt_tokens=8, priority=2)
    ring7.event("preempt", request="pr1", reason="admission",
                priority=2, generated=3, blocks_freed=2)
    ring7.event("resume", request="pr1", generated=3, preemptions=1)
    ring7.event("retire", request="pr1", status="finished", generated=6,
                spec_drafted=0, spec_accepted=0)
    ring7.event("cancel", request="pr2", status="cancelled", generated=1)
    digest = obs.tracing.request_summary("pr1", recorder=ring7)
    check(digest["preemptions"] == 1 and digest["status"] == "finished"
          and digest["retired"],
          f"preempt/resume digest wrong: {digest}")
    digest2 = obs.tracing.request_summary("pr2", recorder=ring7)
    check(digest2["status"] == "cancelled" and not digest2["retired"],
          f"cancel digest wrong: {digest2}")
    fr7 = obs.tracing.FlightRecorder(recorder=ring7, min_interval_s=0.0)
    d7 = tempfile.mkdtemp(prefix="sc_resil_")
    try:
        fr7.arm(d7, window_s=60.0)
        p = fr7.trigger("preemption", request="pr1",
                        preempt_reason="kv_alloc", step=7,
                        blocks_freed=2, generated=3)
        dump = obs.tracing.load_dump(p)
        check(dump["reason"] == "preemption"
              and dump["context"].get("preempt_reason") == "kv_alloc"
              and dump["context"].get("blocks_freed") == 2
              and "pr1" in dump["requests"],
              f"preemption dump context wrong: {dump['context']}")
        check(any(s["name"] == "preempt" for s in dump["spans"]),
              "preemption dump lost the preempt event")
        p2 = fr7.trigger("operator_abort", signal="KeyboardInterrupt",
                         step=9)
        dump2 = obs.tracing.load_dump(p2)
        check(dump2["reason"] == "operator_abort"
              and dump2["context"].get("signal") == "KeyboardInterrupt"
              and isinstance(dump2["metrics"], dict),
              f"operator_abort dump wrong: {dump2['context']}")
    finally:
        shutil.rmtree(d7, ignore_errors=True)

    # host-step fast path (ISSUE 20): the serve_host_phase_seconds
    # histogram's bounded six-phase label set, the work-segment /
    # assembly counter families, and the step-input copy-bytes counter
    # whose steady-state zero the serve_host gate pins — stdlib-only
    reg8 = obs.MetricsRegistry()
    hp8 = reg8.histogram("serve_host_phase_seconds", labels=("phase",))
    hp8.labels(phase="schedule").observe(1e-3)
    hp8.labels(phase="build").observe(2e-3)
    hp8.labels(phase="dispatch").observe(3e-3)
    hp8.labels(phase="overlap").observe(0.0)
    hp8.labels(phase="fetch").observe(4e-3)
    hp8.labels(phase="commit").observe(1e-3)
    kids8 = reg8.snapshot()["serve_host_phase_seconds"]["children"]
    check(sorted(kids8) == ["build", "commit", "dispatch", "fetch",
                            "overlap", "schedule"]
          and all(c["count"] == 1 for c in kids8.values()),
          f"host-phase histogram children wrong: {sorted(kids8)}")
    segs8 = reg8.counter("serve_work_segments_total", labels=("event",))
    segs8.labels(event="reused").inc(15)
    segs8.labels(event="rebuilt").inc(3)
    asm8 = reg8.counter("serve_work_assemblies_total", labels=("mode",))
    asm8.labels(mode="incremental").inc(5)
    asm8.labels(mode="full").inc(1)
    copy8 = reg8.counter("serve_step_input_copy_bytes_total")
    copy8.inc(0)        # the fast path's steady state: increments of 0
    prom8 = obs.to_prometheus(reg8)
    for needle in ('serve_work_segments_total{event="reused"} 15',
                   'serve_work_segments_total{event="rebuilt"} 3',
                   'serve_work_assemblies_total{mode="incremental"} 5',
                   'serve_work_assemblies_total{mode="full"} 1',
                   "serve_step_input_copy_bytes_total 0",
                   'serve_host_phase_seconds_bucket{phase="fetch",'
                   'le="+Inf"} 1'):
        check(needle in prom8, f"prometheus output missing {needle!r}")

    # training health (ISSUE 14): telemetry spec grouping + packed
    # layout, the train_group_* gauge families (bounded GL112-safe
    # label sets), the TrainHealthMonitor detector matrix on a
    # synthetic clock, all FOUR dump reasons (non_finite_loss /
    # grad_norm_spike / loss_divergence / data_stall) loadable with
    # their breach_summary digests, and the instrumented-loader
    # surfaces — stdlib-only like everything above
    th = obs.train_health
    specA = th.build_telemetry_spec(
        {"m.embed_tokens.weight": 2, "m.layers.0.attn.q.weight": 2,
         "m.layers.1.mlp.up.weight": 2, "m.layers.0.norm.weight": 1,
         "lm_head.weight": 2}, max_block_buckets=2)
    check(specA.labels == ("embed", "blocks_00_00", "blocks_01_01",
                           "norm_bias", "head"),
          f"telemetry grouping wrong: {specA.labels}")
    vecA = [0.0] * len(specA)
    vecA[0], vecA[1] = 5.0, 1.25
    off = len(th.HEADER_FIELDS)
    vecA[off:off + 4] = [1.0, 4.0, 0.2, 0.0]
    upA = specA.unpack(vecA)
    check(upA["loss"] == 5.0 and upA["groups"]["embed"]["update_ratio"]
          == 0.05, f"telemetry unpack wrong: {upA}")
    try:
        specA.unpack(vecA[:-1])
        check(False, "short telemetry vector not rejected")
    except ValueError:
        pass
    regT = obs.MetricsRegistry()
    th.record_telemetry(upA, registry=regT)
    snapT = regT.snapshot()
    for fam in ("train_loss", "train_grad_norm",
                "train_group_grad_norm", "train_group_param_norm",
                "train_group_update_ratio", "train_group_nonfinite"):
        check(fam in snapT, f"telemetry gauge family missing: {fam}")
    check(snapT["train_group_grad_norm"]["children"]["embed"]["value"]
          == 1.0, "group gauge value wrong")
    check(set(snapT["train_group_grad_norm"]["children"])
          == set(specA.labels),
          "group gauge label set != spec labels (cardinality leak?)")

    ringT = obs.tracing.SpanRecorder()
    frT = obs.tracing.FlightRecorder(recorder=ringT, min_interval_s=0.0)
    dT = tempfile.mkdtemp(prefix="sc_trainhealth_")
    try:
        frT.arm(dT)
        monT = obs.TrainHealthMonitor(
            window_s=100.0, min_count=3, loss_spike_mads=6.0,
            grad_spike_mads=6.0, update_ratio_bounds=(1e-9, 1.0),
            data_stall_s=0.5, cooldown_s=1000.0, registry=regT,
            recorder=ringT, flight_recorder=frT)
        groupsOK = {"embed": {"grad_norm": 0.5, "param_norm": 2.0,
                              "update_norm": 0.01,
                              "update_ratio": 0.005, "nonfinite": 0.0}}
        for i in range(6):          # healthy baseline: quiet
            monT.observe_step(i, 4.8, 1.3, groups=groupsOK,
                              now=float(i))
        check(monT.breaches_total == 0,
              f"healthy synthetic run breached: {monT.breach_counts}")
        # loss spike -> loss_divergence; sustained -> still once
        monT.observe_step(6, 60.0, 1.3, now=6.0)
        monT.observe_step(7, 60.0, 1.3, now=7.0)
        # grad spike -> grad_norm_spike
        monT.observe_step(8, 4.8, 50.0, now=8.0)
        # NaN -> non_finite_loss, transition-fired exactly once
        monT.observe_step(9, float("nan"), float("nan"), now=9.0)
        monT.observe_step(10, float("nan"), float("nan"), now=10.0)
        # loader stall -> data_stall
        check(monT.observe_data_wait(2.0, now=11.0) is True,
              "data stall not detected")
        check(monT.breach_counts == {"loss_spike": 1, "grad_spike": 1,
                                     "non_finite": 1, "data_stall": 1},
              f"detector matrix wrong: {monT.breach_counts}")
        bcT = regT.snapshot()["train_health_breaches_total"]["children"]
        check(sum(c["value"] for c in bcT.values()) == 4,
              f"breach counter family wrong: {bcT}")
        reasons = sorted(
            obs.load_dump(p)["reason"] for p in frT.dumps)
        check(reasons == ["data_stall", "grad_norm_spike",
                          "loss_divergence", "non_finite_loss"],
              f"train-health dump reasons wrong: {reasons}")
        for p in frT.dumps:         # all four schemas + digests
            dump = obs.load_dump(p)
            dg = th.breach_summary(dump)
            check(dg["reason"] == dump["reason"]
                  and dg["check"] in th.CHECKS
                  and th.DUMP_REASONS[dg["check"]] == dump["reason"],
                  f"breach digest wrong for {dump['reason']}: {dg}")
        try:
            th.breach_summary({"reason": "slo_burn_rate"})
            check(False, "breach_summary accepted a foreign dump")
        except ValueError:
            pass
        # the instrumented loader: wait histogram + batch counter +
        # data_wait spans, stall routed through the monitor
        regL = obs.MetricsRegistry()
        ringL = obs.tracing.SpanRecorder()
        outL = list(th.instrument_loader(
            iter([1, 2, 3]), registry=regL, recorder=ringL,
            queue_depth=lambda: 2))
        check(outL == [1, 2, 3], "instrumented loader altered batches")
        snapL = regL.snapshot()
        check(snapL["train_data_batches_total"]["children"][""]["value"]
              == 3, "loader batch counter wrong")
        check(snapL["train_data_wait_seconds"]["children"][""]["count"]
              == 3, "loader wait histogram wrong")
        check(snapL["train_data_queue_depth"]["children"][""]["value"]
              == 2, "loader queue-depth gauge wrong")
        check(sum(1 for s in ringL.spans()
                  if s["name"] == "data_wait") == 3,
              "data_wait spans missing")
        th.pop_data_wait()          # drain the module accumulator
        th.add_data_wait(0.5)
        check(th.pop_data_wait() == 0.5 and th.pop_data_wait() == 0.0,
              "pending data-wait accumulator wrong")
        try:
            obs.TrainHealthMonitor(window_s=0)
            check(False, "window_s=0 not rejected")
        except ValueError:
            pass
        try:
            obs.TrainHealthMonitor(update_ratio_bounds=(2.0, 1.0))
            check(False, "inverted update_ratio_bounds not rejected")
        except ValueError:
            pass
    finally:
        shutil.rmtree(dT, ignore_errors=True)

    # serving gateway (ISSUE 12): the front-door package must import
    # stdlib-only, its SSE framing must round-trip, its body/healthz
    # validators must hold their contracts, its metric families must
    # export under fixed label sets, and parse_prometheus must invert
    # to_prometheus — all in a bare (jax-less) container
    try:
        srv = _load_serving()
    except Exception as e:
        failures.append(
            f"standalone (pre-jax) serving import failed: {e}")
        return failures
    frame = srv.format_event("token", {"tokens": [5, 9], "step": 3,
                                       "request": "r1", "index": 0})
    check(frame.startswith(b"event: token\ndata: ")
          and frame.endswith(b"\n\n"),
          f"SSE frame framing wrong: {frame!r}")
    evs = srv.parse_events(frame + srv.format_event(
        "end", {"status": "finished", "tokens": [5, 9]}))
    check(evs == [("token", {"tokens": [5, 9], "step": 3,
                             "request": "r1", "index": 0}),
                  ("end", {"status": "finished", "tokens": [5, 9]})],
          f"SSE parse roundtrip wrong: {evs}")
    inc = list(srv.iter_events([":comment\n", "data: {\"a\": 1}\n",
                                "\n"]))
    check(inc == [("message", {"a": 1})],
          f"SSE bare-data/comment handling wrong: {inc}")

    spec, err = srv.validate_generate_body(
        {"prompt": [1, 2], "max_new_tokens": 4, "priority": 1,
         "deadline_steps": 3, "spec_k": 2, "stream": False})
    check(err is None and spec["prompt"] == [1, 2]
          and spec["stream"] is False and spec["deadline_steps"] == 3,
          f"generate-body happy path wrong: {spec} {err}")
    for bad in ({"prompt": [], "max_new_tokens": 1},
                {"prompt": [1], "max_new_tokens": 0},
                {"prompt": [1.5], "max_new_tokens": 1},
                {"prompt": [1], "max_new_tokens": 1, "priority": -1},
                {"prompt": [1], "max_new_tokens": 1, "stream": "yes"},
                {"prompt": [1], "max_new_tokens": 1, "bogus": 1},
                "not a dict"):
        s, e = srv.validate_generate_body(bad)
        check(s is None and isinstance(e, str),
              f"generate-body validator let {bad!r} through")

    hz = {"schema": srv.HEALTHZ_SCHEMA, "status": "ok", "reason": None,
          "inflight": 0, "queue_depth": 0, "steps": 5, "finished": 2}
    check(srv.validate_healthz(hz) is hz, "healthz happy path rejected")
    srv.validate_healthz(dict(hz, status="degraded",
                              reason="slo_burn"))
    for bad in (dict(hz, schema="x/1"),
                dict(hz, status="meh"),
                dict(hz, status="degraded", reason=None),
                {k: v for k, v in hz.items() if k != "steps"},
                dict(hz, inflight=-1)):
        try:
            srv.validate_healthz(bad)
            check(False, f"validate_healthz accepted {bad!r}")
        except ValueError:
            pass

    # gateway metric families: fixed label sets, present in exposition
    inst = obs.instrument
    inst.gateway_request_seconds().labels(route="generate").observe(0.01)
    inst.gateway_stream_seconds().observe(0.5)
    inst.gateway_responses().labels(route="generate", code="200").inc()
    inst.gateway_live_connections().set(2)
    inst.gateway_live_streams().set(1)
    inst.gateway_sse_pending_events().set(0)
    inst.gateway_sse_events().labels(event="token").inc(3)
    inst.gateway_health_transitions().labels(to="degraded").inc()
    prom8 = obs.to_prometheus()
    for needle in ("# TYPE gateway_request_seconds histogram",
                   'gateway_responses_total{route="generate",code="200"} 1',
                   "gateway_live_connections 2",
                   'gateway_sse_events_total{event="token"} 3',
                   'gateway_health_transitions_total{to="degraded"} 1'):
        check(needle in prom8,
              f"gateway family missing from exposition: {needle!r}")
    parsed = obs.parse_prometheus(prom8)
    check(parsed["gateway_request_seconds"]["kind"] == "histogram"
          and any(n == "gateway_request_seconds_count"
                  and lbl.get("route") == "generate" and v == 1
                  for n, lbl, v
                  in parsed["gateway_request_seconds"]["samples"]),
          "parse_prometheus lost the gateway histogram")
    check(any(n == "gateway_responses_total" and v == 1
              and lbl == {"route": "generate", "code": "200"}
              for n, lbl, v
              in parsed["gateway_responses_total"]["samples"]),
          "parse_prometheus lost the labeled counter")
    # escaping survives the roundtrip (the PR-8 help/label split)
    reg9 = obs.MetricsRegistry()
    reg9.counter("rt_esc_total", labels=("q",)).labels(
        q='a"b\\c\nd').inc()
    rt = obs.parse_prometheus(obs.to_prometheus(reg9))
    check(rt["rt_esc_total"]["samples"][0][1]["q"] == 'a"b\\c\nd',
          f"label escaping did not round-trip: "
          f"{rt['rt_esc_total']['samples']}")
    # the adversarial case: a LITERAL backslash followed by 'n' (a
    # Windows path, a repr'd error) — unescaping must run one
    # left-to-right pass, not sequential replaces
    reg10 = obs.MetricsRegistry()
    reg10.counter("rt_esc2_total", labels=("p",)).labels(
        p="back\\nslash\\\\x").inc()
    rt2 = obs.parse_prometheus(obs.to_prometheus(reg10))
    check(rt2["rt_esc2_total"]["samples"][0][1]["p"]
          == "back\\nslash\\\\x",
          f"literal-backslash label did not round-trip: "
          f"{rt2['rt_esc2_total']['samples']}")
    lone = obs.parse_prometheus('x_bucket{le="+Inf"} 3\n')
    check(lone["x_bucket"]["samples"]
          == [("x_bucket", {"le": "+Inf"}, 3.0)],
          f"parse_prometheus mishandled a bucket sample: {lone}")
    try:
        obs.parse_prometheus("not a metric line at all {{{")
        check(False, "parse_prometheus accepted garbage")
    except ValueError:
        pass

    # multi-replica router (ISSUE 19): the policy registry and the
    # route-choice math are pure stdlib (no engine, no jax), and the
    # router/replica metric families must export under their bounded
    # label sets (`policy` a fixed literal set, `replica` world-bounded
    # like `device`)
    check(set(srv.POLICIES)
          == {"round_robin", "least_loaded", "prefix_affinity"},
          f"routing policy registry drifted: {sorted(srv.POLICIES)}")
    RouteView = srv.router.RouteView
    rr = srv.RoundRobinPolicy()
    rv = RouteView((0, 1), {0: 3, 1: 0},
                   {0: frozenset({"k1"}), 1: frozenset()},
                   ("k1", "k2"))
    check([rr.choose(rv) for _ in range(4)] == [0, 1, 0, 1],
          "round-robin rotation wrong")
    check(srv.LeastLoadedPolicy().choose(rv) == 1,
          "least-loaded did not pick the idle replica")
    aff = srv.PrefixAffinityPolicy(imbalance_cap=4)
    check(aff.choose(rv) == (0, "hit"),
          "affinity missed the replica holding the prefix")
    check(srv.PrefixAffinityPolicy(imbalance_cap=2).choose(rv)
          == (1, "miss"),
          "imbalance cap did not veto the overloaded match")
    check(srv.PrefixAffinityPolicy().choose(
        RouteView((0, 1), {0: 0, 1: 0}, {0: frozenset(),
                                         1: frozenset()},
                  ("k1",))) == (0, "miss"),
          "no-match affinity did not fall back to least-loaded")
    try:
        srv.EngineRouter([])
        check(False, "empty replica pool not rejected")
    except ValueError:
        pass
    inst.routed_requests().labels(policy="prefix_affinity",
                                  replica="0").inc(2)
    inst.router_affinity_hits().inc()
    inst.router_affinity_misses().inc()
    inst.router_resubmits().labels(replica="1").inc()
    inst.router_replica_inflight().labels(replica="0").set(2)
    inst.router_replicas_live().set(2)
    promR = obs.to_prometheus()
    for needle in (
            'routed_requests_total{policy="prefix_affinity",'
            'replica="0"} 2',
            "router_affinity_hits_total 1",
            "router_affinity_misses_total 1",
            'router_resubmits_total{replica="1"} 1',
            'router_replica_inflight{replica="0"} 2',
            "router_replicas_live 2"):
        check(needle in promR,
              f"router family missing from exposition: {needle!r}")
    parsedR = obs.parse_prometheus(promR)
    check(any(n == "routed_requests_total" and v == 2
              and lbl == {"policy": "prefix_affinity", "replica": "0"}
              for n, lbl, v
              in parsedR["routed_requests_total"]["samples"]),
          "parse_prometheus lost the routed-requests counter")

    # kernel-autotune families (ISSUE 16): sweep accounting and the
    # winner-config gauges must export under their bounded label sets
    # (kernel names are code literals, `param` is a fixed 3-tuple) —
    # stdlib-only like everything above
    inst.autotune_trials().labels(kernel="ragged_paged_attention").inc(9)
    inst.autotune_cache_hits().inc(2)
    inst.autotune_cache_misses().inc()
    for param, val in (("pack", 4), ("prefill_chunk", 8),
                       ("buffer_depth", 2)):
        inst.autotune_winner().labels(
            kernel="ragged_paged_attention", param=param).set(val)  # graftlint: disable=GL112 - fixed 3-element literal label set
    prom11 = obs.to_prometheus()
    for needle in (
            'autotune_trials_total{kernel="ragged_paged_attention"} 9',
            "autotune_cache_hits_total 2",
            "autotune_cache_misses_total 1",
            "# TYPE autotune_winner_config gauge",
            'autotune_winner_config{kernel="ragged_paged_attention"'
            ',param="buffer_depth"} 2'):
        check(needle in prom11,
              f"autotune family missing from exposition: {needle!r}")
    parsed11 = obs.parse_prometheus(prom11)
    check(any(n == "autotune_winner_config" and v == 8
              and lbl.get("param") == "prefill_chunk"
              for n, lbl, v
              in parsed11["autotune_winner_config"]["samples"]),
          "parse_prometheus lost the autotune winner gauge")

    # fleet layer (ISSUE 18): per-rank mirroring through RankExporter
    # (atomic snapshot files + manifest, seq adoption), merge math
    # (counters sum exactly, fixed-bucket histograms merge exactly so
    # fleet quantiles are real, gauges keep rank-labeled children with
    # rollups), the prometheus-scrape ingestion path, and the
    # FleetMonitor straggler detector fire/no-fire on synthetic
    # clocks with a schema-valid fleet_straggler dump — stdlib-only
    # like everything above
    fdir = tempfile.mkdtemp(prefix="sc_fleet_")
    try:
        regs = []
        for rank in range(2):
            freg = obs.MetricsRegistry()
            freg.counter("fl_tokens_total").inc(10 * (rank + 1))
            fh = freg.histogram("fl_step_seconds",
                                buckets=(0.01, 0.1, 1.0))
            for v in ((0.005, 0.05) if rank == 0 else (0.5, 2.0)):
                fh.observe(v)
            freg.gauge("fl_depth").set(float(rank + 3))
            regs.append(freg)
        exps = [obs.RankExporter(fdir, r, 2, run_id="sc",
                                 registry=regs[r], interval_s=0.0)
                for r in range(2)]
        for e in exps:
            e.export()
        snaps = obs.discover_snapshots(fdir, run_id="sc")
        check(sorted(snaps) == [0, 1],
              f"fleet discovery missed ranks: {sorted(snaps)}")
        man = obs.load_fleet_manifest(fdir)
        check(man["run_id"] == "sc"
              and sorted(man["ranks"]) == ["0", "1"]
              and all(man["ranks"][str(r)]["seq"] == snaps[r]["seq"]
                      for r in snaps),
              "fleet manifest does not round-trip the rank files")
        # adoption: a re-armed exporter continues the rank's seq
        check(obs.RankExporter(fdir, 0, 2, run_id="sc",
                               registry=regs[0]).seq
              == snaps[0]["seq"],
              "re-armed RankExporter did not adopt the previous seq")
        view = obs.merge_snapshots(snaps)
        tok = view["metrics"]["fl_tokens_total"]["children"][""]
        check(tok["value"] == 30.0,
              f"fleet counter sum not exact: {tok['value']}")
        hch = view["metrics"]["fl_step_seconds"]["children"][""]
        check(hch["bucket_counts"] == [1, 1, 1, 1]
              and hch["count"] == 4,
              f"fleet histogram merge not exact: {hch}")
        q95 = obs.merged_quantile(view, "fl_step_seconds", 0.95)
        check(q95 is not None and 0.1 < q95 <= 1.0,
              f"fleet p95 {q95} outside the pooled crossing bucket")
        dfam = view["metrics"]["fl_depth"]
        check(dfam["labelnames"] == ["rank"]
              and dfam["children"]["0"]["value"] == 3.0
              and dfam["children"]["1"]["value"] == 4.0,
              "merged gauge lost its rank-labeled children")
        roll = obs.gauge_rollups(view, "fl_depth")[""]
        check(roll["min"] == 3.0 and roll["max"] == 4.0
              and roll["mean"] == 3.5,
              f"gauge rollups wrong: {roll}")
        # scrape path: exposition text -> snapshot -> same merge
        scraped = obs.snapshot_from_prometheus(
            obs.to_prometheus(regs[0]))
        sch = scraped["fl_step_seconds"]["children"][""]
        check(sch["bucket_counts"]
              == regs[0].snapshot()["fl_step_seconds"]["children"][""][
                  "bucket_counts"],
              "snapshot_from_prometheus did not de-cumulate buckets")
        # straggler detector on synthetic clocks: rank 1's dispatch
        # mean sits far over the fleet median; rank 0 must stay quiet
        ddir = os.path.join(fdir, "dumps")
        monf = obs.FleetMonitor(window_s=60.0, min_count=3,
                                mad_factor=4.0, abs_floor_s=0.005,
                                checks=(("dispatch",
                                         "fl_dispatch_seconds"),),
                                registry=obs.MetricsRegistry(),
                                dump_dir=ddir, min_interval_s=0.0)
        sregs, shs, seqs = [], [], [0, 0, 0]

        def feed(rank, t):
            seqs[rank] += 1
            monf.ingest({"schema": obs.fleet_obs.SNAPSHOT_SCHEMA,
                         "run_id": "sc", "rank": rank, "world_size": 3,
                         "seq": seqs[rank],
                         "clock": {"time": 0.0,
                                   "monotonic": 100.0 + t,
                                   "perf_us": 0.0},
                         "metrics": sregs[rank].snapshot(),
                         "spans": []})

        for rank in range(3):
            sregs.append(obs.MetricsRegistry())
            shs.append(sregs[rank].histogram(
                "fl_dispatch_seconds", buckets=(0.01, 0.1, 1.0, 10.0)))
        for t in range(5):
            for rank in range(3):
                if t:
                    shs[rank].observe(0.02)
                feed(rank, t)
        check(monf.check() == [],
              "straggler detector fired on a symmetric healthy fleet")
        for t in range(5, 8):
            for rank in range(3):
                shs[rank].observe(0.02 if rank < 2 else 2.0)
                feed(rank, t)
        fired = monf.check()
        check(len(fired) == 1 and fired[0]["rank"] == 2
              and fired[0]["check"] == "dispatch",
              f"straggler detector wrong breach set: {fired}")
        fdumps = [f for f in os.listdir(ddir)
                  if f.startswith("flightrec_fleet_straggler")]
        check(len(fdumps) == 1,
              f"expected one fleet_straggler dump: {fdumps}")
        if fdumps:
            fd = obs.load_dump(os.path.join(ddir, fdumps[0]))
            fctx = fd.get("context", {})
            check(fd["reason"] == "fleet_straggler"
                  and fctx.get("rank") == 2
                  and sum(json.loads(fctx["rank_hist"])) > 0
                  and sum(json.loads(fctx["fleet_hist"])) > 0,
                  "fleet_straggler dump schema/witnesses wrong")
    finally:
        shutil.rmtree(fdir, ignore_errors=True)
    return failures


def main():
    ap = argparse.ArgumentParser(
        description="dump or selfcheck the observability registry")
    ap.add_argument("--format", default="json",
                    choices=["prometheus", "json", "chrome"])
    ap.add_argument("--selfcheck", action="store_true",
                    help="exercise the metrics core and exit 0/1 "
                         "(tier-0 gate; runs without jax)")
    args = ap.parse_args()
    if args.selfcheck:
        failures = selfcheck()
        if failures:
            print(f"metrics selfcheck: FAIL ({len(failures)} problems)")
            for f in failures:
                print("  " + f)
            return 1
        print("metrics selfcheck: OK")
        return 0
    print(dump(args.format))
    return 0


if __name__ == "__main__":
    sys.exit(main())
