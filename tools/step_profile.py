"""Device-profile the headline pretrain step and print the evidence table.

Round-4 verdict asked for device-profile evidence of where the step time
goes (the measured step sat 1.9x above the builder's roofline floors with
no xprof capture backing the explanation). This tool captures an XLA
device trace of the flagship step via jax.profiler, then aggregates
per-op `device_duration_ps` and `bytes_accessed` into:

  - total device-busy time per step and aggregate HBM bandwidth
    utilization vs the chip's 819 GB/s peak,
  - time/bytes by HLO category (matmul fusions, pallas custom-calls,
    loop fusions, data formatting, ...),
  - the top-N individual HBM consumers.

Usage:  python tools/step_profile.py [--iters 4] [--json out.json]

Round-5 finding recorded in BASELINE.md: the step was never
memory-bound (41% aggregate HBM BW) — 39% of device time was the flash
attention custom-calls (f32 MXU operands + undersized fwd tiles), which
bytes_accessed cannot see because the profiler reports 0 bytes for
custom-calls.
"""
import argparse
import collections
import glob
import gzip
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

HBM_PEAK = {"v5 lite": 819e9, "v5e": 819e9, "v5p": 2765e9, "v4": 1228e9}


def capture(step_fn, iters):
    import jax
    d = tempfile.mkdtemp(prefix="step_profile_")
    jax.profiler.start_trace(d)
    step_fn(iters)
    jax.profiler.stop_trace()
    return d


def parse(trace_dir, iters):
    f = sorted(glob.glob(trace_dir + "/**/*.trace.json.gz",
                         recursive=True))[-1]
    with gzip.open(f) as fh:
        tr = json.load(fh)
    ev = tr.get("traceEvents")
    if not isinstance(ev, list):
        raise SystemExit(
            f"step_profile: {f} has no traceEvents list — "
            "profiler schema drift or truncated capture")
    tids = {e["tid"]: e["args"]["name"] for e in ev
            if e.get("ph") == "M" and e.get("name") == "thread_name"
            and e.get("pid") == 3}
    ops = [e for e in ev if e.get("ph") == "X" and e.get("pid") == 3
           and tids.get(e.get("tid")) == "XLA Ops" and e.get("args")]
    total_ps = sum(int(e["args"].get("device_duration_ps", 0)) for e in ops)
    total_bytes = sum(int(e["args"].get("bytes_accessed", 0)) for e in ops)
    bycat = collections.defaultdict(lambda: [0, 0])
    byname = collections.defaultdict(lambda: [0, 0, ""])
    for e in ops:
        a = e["args"]
        ps = int(a.get("device_duration_ps", 0))
        by = int(a.get("bytes_accessed", 0))
        bycat[a.get("hlo_category", "?")][0] += ps
        bycat[a.get("hlo_category", "?")][1] += by
        r = byname[e["name"]]
        r[0] += ps
        r[1] += by
        r[2] = a.get("long_name", "")[:120]
    return {
        "device_ms_per_step": total_ps / 1e9 / iters,
        "bytes_per_step": total_bytes / iters,
        "by_category": {c: {"ms": v[0] / 1e9 / iters,
                            "gb": v[1] / 1e9 / iters}
                        for c, v in sorted(bycat.items(),
                                           key=lambda kv: -kv[1][0])},
        "top_hbm_ops": [
            {"name": n, "ms": v[0] / 1e9 / iters, "gb": v[1] / 1e9 / iters,
             "hlo": v[2]}
            for n, v in sorted(byname.items(),
                               key=lambda kv: -kv[1][1])[:10]],
        "top_time_ops": [
            {"name": n, "ms": v[0] / 1e9 / iters, "gb": v[1] / 1e9 / iters,
             "hlo": v[2]}
            for n, v in sorted(byname.items(),
                               key=lambda kv: -kv[1][0])[:10]],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    import jax
    import numpy as np

    from paddle_tpu.models import LlamaForCausalLM, pretrain
    on_tpu = jax.devices()[0].platform == "tpu"
    # the SAME flagship shape bench.py benchmarks — shared helper so the
    # profile always describes the headline step
    cfg, batch, seq = pretrain.flagship_config(on_tpu)
    model = LlamaForCausalLM(cfg)
    mesh = pretrain.make_mesh(1, dp=1, fsdp=1, mp=1, sp=1)
    params, opt_state, meta = pretrain.make_train_state(model, mesh)
    step = pretrain.make_train_step(model, mesh, meta)
    rng = np.random.default_rng(0)

    def fresh():
        return pretrain.shard_batch(
            {"input_ids": rng.integers(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int32),
             "labels": rng.integers(0, cfg.vocab_size,
                                    (batch, seq)).astype(np.int32)}, mesh)

    state = [params, opt_state]

    def run(n):
        for _ in range(n):
            state[0], state[1], loss, _ = step(state[0], state[1], fresh())
        float(loss)

    run(3)  # warm + compile
    d = capture(run, args.iters)
    out = parse(d, args.iters)
    shutil.rmtree(d, ignore_errors=True)

    kind = jax.devices()[0].device_kind.lower()
    peak = next((v for k, v in HBM_PEAK.items() if k in kind), 819e9)
    bw = out["bytes_per_step"] / (out["device_ms_per_step"] / 1e3)
    out["hbm_bw_utilization"] = bw / peak
    print(f"device busy: {out['device_ms_per_step']:.1f} ms/step | "
          f"bytes: {out['bytes_per_step']/1e9:.1f} GB/step | "
          f"aggregate HBM BW: {bw/1e9:.0f} GB/s "
          f"({out['hbm_bw_utilization']*100:.0f}% of peak)")
    print("\nby HLO category (ms/step, GB/step):")
    for c, v in out["by_category"].items():
        print(f"  {v['ms']:8.2f} ms  {v['gb']:7.2f} GB  {c}")
    print("\ntop HBM consumers:")
    for r in out["top_hbm_ops"]:
        print(f"  {r['gb']:6.2f} GB {r['ms']:7.2f} ms  {r['name'][:60]}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"\nwrote {args.json}")


if __name__ == "__main__":
    main()
