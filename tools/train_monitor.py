#!/usr/bin/env python
"""Training health monitor drive — and the `train_health` CI gate.

Drives a short sharded pretrain (the dp2 x fsdp2 x mp2 virtual-8-device
mesh, the dryrun_multichip pattern) with the per-layer-group telemetry
and the TrainHealthMonitor on, healthy AND under injected faults
(paddle_tpu/testing/faults.py TrainFaultInjector), and proves the
ISSUE-14 contract end to end:

* **neutrality** — telemetry-on is loss-BIT-exact vs telemetry-off
  over the same seeded workload, and compile-count-neutral after
  warmup (the packed in-graph vector is a pure extra output; one bulk
  host fetch per cadence, zero per-tensor syncs).
* **healthy** — a monitored run through the REAL instrumented
  DataLoader (instrument=True: wait histograms, queue-depth gauge,
  `data_wait` chrome spans) raises ZERO breaches, and reports the
  per-group norm snapshot plus the data-wait/host/dispatch step-phase
  split.
* **faults** — each injected production failure fires exactly its
  detector(s), exactly once, with a schema-valid loadable flight dump:
  - a NaN'd batch (out-of-vocab ids -> NaN embeddings) -> `non_finite`
    -> a `non_finite_loss` dump, and training CONTINUES (degrade,
    don't crash — the PR-11 discipline);
  - an lr spike (one update at 64x lr through the step's lr_scale=
    program) -> `grad_spike` + `loss_spike` on the next step ->
    `grad_norm_spike` + `loss_divergence` dumps;
  - a throttled loader (injected sleep upstream of the wait
    measurement) -> `data_stall` -> a `data_stall` dump.

Modes:
  python tools/train_monitor.py                  # report
  python tools/train_monitor.py --json out.json
  python tools/train_monitor.py --check tools/train_health.json

The --check gate (wired into tools/lint.sh next to the serve gates)
compares the report against the committed baseline: exact fired-count
matrices per fault, dump reasons, zero healthy breaches, loss
exactness, zero new compiles after warmup, and the exact bounded group
label set.
"""
import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPORT_SCHEMA = "paddle_tpu.train_health_report/1"
BASELINE_SCHEMA = "paddle_tpu.train_health/1"

# the gate workload: tiny llama on the virtual 8-device mesh
MESH = {"dp": 2, "fsdp": 2, "mp": 2}
BATCH, SEQ, VOCAB = 8, 16, 128


def _force_virtual_devices(n=8):
    """The dryrun_multichip pattern: must run before jax initializes."""
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _fresh_run(telemetry=False, monitor=None):
    """Freshly seeded model + sharded state + train step — every leg
    starts from IDENTICAL parameters (the step donates its buffers, so
    state can never be shared across legs)."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, pretrain

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=VOCAB, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=SEQ, dtype="float32")
    model = LlamaForCausalLM(cfg)
    n_dev = MESH["dp"] * MESH["fsdp"] * MESH["mp"]
    mesh = pretrain.make_mesh(n_dev, **MESH)
    params, opt_state, meta = pretrain.make_train_state(model, mesh)
    step = pretrain.make_train_step(model, mesh, meta,
                                    telemetry=telemetry, monitor=monitor)
    return mesh, params, opt_state, step


def _batches(n, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    return [{"input_ids": rng.integers(0, VOCAB, (BATCH, SEQ)).astype(
                 np.int32),
             "labels": rng.integers(0, VOCAB, (BATCH, SEQ)).astype(
                 np.int32)}
            for _ in range(n)]


def _monitor(base_cfg, flight_dir, **overrides):
    from paddle_tpu import observability as obs
    flight = obs.FlightRecorder(min_interval_s=0.0)
    flight.arm(flight_dir)
    return obs.TrainHealthMonitor.from_config(
        base_cfg, flight_recorder=flight, **overrides)


def _collect_dumps(flight):
    """Load + schema-validate every dump the leg's recorder wrote."""
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import train_health as th
    out, ok = [], True
    for path in flight.dumps:
        try:
            dump = obs.load_dump(path)
            digest = th.breach_summary(dump)
            out.append({"reason": dump["reason"],
                        "check": digest["check"], "valid": True})
        except (OSError, ValueError) as e:
            ok = False
            out.append({"reason": os.path.basename(path),
                        "check": None, "valid": False, "error": str(e)})
    return out, ok


def neutrality_leg(steps=6):
    """Telemetry-on vs telemetry-off: loss bit-exactness + zero
    compiles after warmup. Warmup is the first TWO steps — step 0
    compiles the program, step 1 recompiles once when its inputs
    arrive as step 0's donated-aliased outputs (pre-existing behavior,
    identical with telemetry off; verified both ways here)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.models import pretrain

    obs.install_compile_watch()

    def backend_compiles():
        snap = obs.get_registry().snapshot().get("jax_compiles_total", {})
        return sum(c["value"]
                   for name, c in snap.get("children", {}).items()
                   if name.startswith("backend_compile"))

    def drive(telemetry):
        mesh, params, opt_state, step = _fresh_run(telemetry=telemetry)
        losses = []
        after_warmup = None
        for i, b in enumerate(_batches(steps)):
            if i == 2:
                after_warmup = backend_compiles()
            params, opt_state, loss, gnorm = step(
                params, opt_state, pretrain.shard_batch(b, mesh))
            losses.append(float(loss))
        return losses, backend_compiles() - after_warmup

    losses_off, _ = drive(False)
    losses_on, new_compiles = drive(True)
    return {
        "steps": steps,
        "losses_off": losses_off,
        "losses_on": losses_on,
        "loss_exact": losses_off == losses_on,
        "new_compiles_after_warmup": new_compiles,
    }


def healthy_leg(monitor_cfg, steps=10):
    """Monitored run through the instrumented DataLoader: zero
    breaches, per-group norms, step-phase split."""
    import numpy as np
    from paddle_tpu import observability as obs
    from paddle_tpu.io import DataLoader
    from paddle_tpu.models import pretrain
    from paddle_tpu.observability import train_health as th

    th.pop_data_wait()      # no stale wait from a previous leg
    flight_dir = tempfile.mkdtemp(prefix="train_health_ok_")
    try:
        mon = _monitor(monitor_cfg, flight_dir, data_stall_s=30.0)
        mesh, params, opt_state, step = _fresh_run(monitor=mon)

        samples = []
        for b in _batches(steps):
            for j in range(BATCH):
                samples.append({"input_ids": b["input_ids"][j],
                                "labels": b["labels"][j]})
        loader = DataLoader(
            samples, batch_size=BATCH, num_workers=2, instrument=True,
            collate_fn=lambda rows: {k: np.stack([r[k] for r in rows])
                                     for k in rows[0]})
        loader.health_monitor = mon
        n = 0
        for b in loader:
            params, opt_state, loss, gnorm = step(
                params, opt_state, pretrain.shard_batch(b, mesh))
            n += 1
            if n >= steps:
                break
    finally:
        shutil.rmtree(flight_dir, ignore_errors=True)

    reg = obs.get_registry()
    snap = reg.snapshot()

    def gauge_children(name):
        return {k: v["value"]
                for k, v in snap.get(name, {}).get("children",
                                                   {}).items()}

    def p50(name):
        fam = reg.get(name)
        return None if fam is None or not fam.count else \
            fam.quantile(0.5)

    groups = {}
    for label, g in gauge_children("train_group_grad_norm").items():
        groups[label] = {
            "grad_norm": g,
            "param_norm": gauge_children(
                "train_group_param_norm").get(label),
            "update_ratio": gauge_children(
                "train_group_update_ratio").get(label),
        }
    return {
        "steps": n,
        "breaches": mon.breaches_total,
        "breach_counts": dict(mon.breach_counts),
        "data_batches": int(
            snap.get("train_data_batches_total", {}).get(
                "children", {}).get("", {}).get("value", 0)),
        "group_norms": groups,
        "phase_p50_s": {"data_wait": p50("train_data_wait_seconds"),
                        "host": p50("train_host_seconds"),
                        "dispatch": p50("train_step_seconds")},
    }


def nan_batch_leg(monitor_cfg, steps=8, fault_at=5):
    """Out-of-vocab ids at one step -> non-finite loss/grads -> the
    non_finite detector fires ONCE (transition), a non_finite_loss
    dump lands, and the loop runs to completion."""
    from paddle_tpu.models import pretrain
    from paddle_tpu.observability import train_health as th
    from paddle_tpu.testing.faults import TrainFaultInjector

    th.pop_data_wait()
    flight_dir = tempfile.mkdtemp(prefix="train_health_nan_")
    try:
        mon = _monitor(monitor_cfg, flight_dir)
        mesh, params, opt_state, step = _fresh_run(monitor=mon)
        inj = TrainFaultInjector().nan_batch(fault_at)
        completed = 0
        for i, b in enumerate(_batches(steps)):
            b = inj.adjust_batch(i, b)
            params, opt_state, loss, gnorm = step(
                params, opt_state, pretrain.shard_batch(b, mesh))
            completed += 1
        dumps, dumps_valid = _collect_dumps(mon.flight_recorder)
    finally:
        shutil.rmtree(flight_dir, ignore_errors=True)
    return {
        "steps": completed,
        "fault_at": fault_at,
        "injected": dict(inj.injected),
        "fired": dict(mon.breach_counts),
        "dump_reasons": sorted(d["reason"] for d in dumps),
        "dumps_valid": dumps_valid,
        "continued_after_fault": completed == steps,
    }


def lr_spike_leg(monitor_cfg, steps=10, fault_at=6, factor=4096.0):
    """One update at factor x lr (the lr_scale= program). Fires THREE
    detectors deterministically: update_ratio at the faulted step
    itself (the update/param ratio jumps ~60x over the explosion
    bound — the canonical lr-spike signature), then loss_spike +
    grad_spike at the NEXT step when the blown-up parameters send
    loss/grad-norm out of the rolling median+MAD baseline (4096x is
    tuned for margin: loss 4.87 -> 9.7 vs threshold ~6.8, gnorm
    1.26 -> 10.3 vs ~1.8 — large and seeded-deterministic, yet
    finite, so non_finite stays quiet)."""
    from paddle_tpu.models import pretrain
    from paddle_tpu.observability import train_health as th
    from paddle_tpu.testing.faults import TrainFaultInjector

    th.pop_data_wait()
    flight_dir = tempfile.mkdtemp(prefix="train_health_lr_")
    try:
        mon = _monitor(monitor_cfg, flight_dir)
        mesh, params, opt_state, step = _fresh_run(monitor=mon)
        inj = TrainFaultInjector().lr_spike(fault_at, factor=factor)
        for i, b in enumerate(_batches(steps)):
            params, opt_state, loss, gnorm = step(
                params, opt_state, pretrain.shard_batch(b, mesh),
                lr_scale=inj.lr_scale_for(i))
        dumps, dumps_valid = _collect_dumps(mon.flight_recorder)
    finally:
        shutil.rmtree(flight_dir, ignore_errors=True)
    return {
        "steps": steps,
        "fault_at": fault_at,
        "factor": factor,
        "injected": dict(inj.injected),
        "fired": dict(mon.breach_counts),
        "dump_reasons": sorted(d["reason"] for d in dumps),
        "dumps_valid": dumps_valid,
    }


def data_stall_leg(monitor_cfg, steps=6, stall_at=3, delay_s=1.0):
    """A throttled loader: the injected sleep rides UPSTREAM of the
    instrumented loader's wait measurement, so the stall detector sees
    a real starved pipeline and fires the data_stall dump."""
    from paddle_tpu.models import pretrain
    from paddle_tpu.observability import train_health as th
    from paddle_tpu.testing.faults import TrainFaultInjector

    th.pop_data_wait()
    flight_dir = tempfile.mkdtemp(prefix="train_health_stall_")
    try:
        mon = _monitor(monitor_cfg, flight_dir)
        mesh, params, opt_state, step = _fresh_run(monitor=mon)
        inj = TrainFaultInjector().stall_loader(stall_at,
                                                delay_s=delay_s)
        loader = th.instrument_loader(inj.wrap_loader(_batches(steps)),
                                      monitor=mon)
        for b in loader:
            params, opt_state, loss, gnorm = step(
                params, opt_state, pretrain.shard_batch(b, mesh))
        dumps, dumps_valid = _collect_dumps(mon.flight_recorder)
    finally:
        shutil.rmtree(flight_dir, ignore_errors=True)
    return {
        "steps": steps,
        "stall_at": stall_at,
        "delay_s": delay_s,
        "injected": dict(inj.injected),
        "fired": dict(mon.breach_counts),
        "dump_reasons": sorted(d["reason"] for d in dumps),
        "dumps_valid": dumps_valid,
    }


def build_report(monitor_cfg):
    from paddle_tpu.observability import train_health as th

    mesh, params, opt_state, step = _fresh_run(telemetry=True)
    groups = list(step._telemetry_spec.labels)
    del params, opt_state
    return {
        "schema": REPORT_SCHEMA,
        "workload": {"mesh": dict(MESH), "batch": BATCH, "seq": SEQ,
                     "vocab": VOCAB},
        "monitor": dict(monitor_cfg),
        "groups": groups,
        "checks": list(th.CHECKS),
        "neutrality": neutrality_leg(),
        "healthy": healthy_leg(monitor_cfg),
        "faults": {
            "nan_batch": nan_batch_leg(monitor_cfg),
            "lr_spike": lr_spike_leg(monitor_cfg),
            "data_stall": data_stall_leg(monitor_cfg),
        },
    }


DEFAULT_MONITOR = {
    "window_s": 120.0, "min_count": 4, "loss_spike_mads": 8.0,
    "grad_spike_mads": 8.0, "mad_floor_frac": 0.05,
    "update_ratio_bounds": [1e-9, 1.0], "data_stall_s": 0.3,
    "cooldown_s": 600.0,
}


def print_report(report):
    n = report["neutrality"]
    print(f"neutrality: loss_exact={n['loss_exact']} over {n['steps']} "
          f"steps, {n['new_compiles_after_warmup']} compiles after "
          f"warmup")
    h = report["healthy"]
    ph = h["phase_p50_s"]

    def ms(v):
        return "-" if v is None else f"{v * 1e3:.1f}ms"

    print(f"healthy: {h['breaches']} breaches over {h['steps']} steps "
          f"({h['data_batches']} batches); p50 data-wait "
          f"{ms(ph['data_wait'])} / host {ms(ph['host'])} / dispatch "
          f"{ms(ph['dispatch'])}")
    print(f"{'group':>14} | {'grad_norm':>10} | {'param_norm':>10} | "
          f"{'upd/param':>10}")
    for label in report["groups"]:
        g = h["group_norms"].get(label)
        if g is None:
            continue
        print(f"{label:>14} | {g['grad_norm']:>10.4f} | "
              f"{g['param_norm']:>10.2f} | {g['update_ratio']:>10.2e}")
    for name, leg in report["faults"].items():
        print(f"fault {name}: fired={leg['fired']} "
              f"dumps={leg['dump_reasons']} valid={leg['dumps_valid']}")


def _lookup(report, dotted):
    cur = report
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def check(baseline_path):
    """The train_health gate: schema + exact fired matrices + dump
    reasons + neutrality + bounds, against the committed baseline."""
    with open(baseline_path) as f:
        base = json.load(f)
    if base.get("schema") != BASELINE_SCHEMA:
        print(f"{baseline_path}: not a {BASELINE_SCHEMA} baseline")
        return 1
    report = build_report(base.get("monitor", DEFAULT_MONITOR))
    print_report(report)
    bad = []
    if report.get("schema") != REPORT_SCHEMA:
        bad.append(f"report schema {report.get('schema')!r}")
    for dotted, want in base.get("exact", {}).items():
        got = _lookup(report, dotted)
        if got != want:
            bad.append(f"{dotted}: {got!r} != required {want!r}")
    for dotted, (lo, hi) in base.get("bounds", {}).items():
        got = _lookup(report, dotted)
        if got is None:
            bad.append(f"{dotted}: missing (bounds [{lo}, {hi}])")
        elif not (lo <= got <= hi):
            bad.append(f"{dotted}: {got} outside [{lo}, {hi}]")
    if bad:
        print(f"train_health gate: FAIL ({len(bad)} problems)")
        for b in bad:
            print("  " + b)
        return 1
    print(f"train_health gate OK: {len(base.get('exact', {}))} exact "
          f"fields, {len(base.get('bounds', {}))} bounds")
    return 0


def main():
    ap = argparse.ArgumentParser(
        description="training health monitor drive + train_health gate")
    ap.add_argument("--json", default=None, help="write the report here")
    ap.add_argument("--check", metavar="BASELINE_JSON", default=None,
                    help="gate the report against a committed "
                         "train_health baseline")
    args = ap.parse_args()
    _force_virtual_devices(8)
    if args.check:
        return check(args.check)
    report = build_report(DEFAULT_MONITOR)
    print_report(report)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, default=str)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
