#!/usr/bin/env python
"""Heavy-tail serving load monitor + SLO gate (ISSUE 8).

The production-front-door question the step-count benches cannot
answer: under a heavy-tail workload (Pareto prompt lengths, Poisson
arrivals — the shape real traffic has, not the fixed ragged batch),
with the SAMPLER running, do the WINDOWED p99s hold and does the SLO
engine stay quiet? This tool drives `ContinuousBatchingEngine` with an
attached `SLOMonitor` (observability/slo.py), renders a periodic text
dashboard from the windowed time series, writes a JSON report, and —
via ``--check tools/serve_slo.json`` — gates:

* **windowed p99 TTFT / TPOT** (delta-histogram quantiles over the
  monitored run, not process lifetime) under the declared objectives,
* **zero burn-rate breaches** across both evaluation windows,
* **zero new compile buckets** after the warmup run,
* **monitor neutrality**: the monitored and unmonitored runs must be
  token-exact with identical step counts (the PR 6 trace-leg contract,
  extended to the SLO engine),
* the host-deterministic workload accounting (steps, tokens, arrival
  schedule) against the committed baseline.

Workload generation is config-seeded (one `np.random.default_rng` per
leg) and arrivals live on the STEP clock, so every count gated here is
host-deterministic; wall-clock latencies are evaluated only against
the generous declared objectives (off-TPU they time the Pallas
interpreter, not the chip — same caveat as every serve_bench leg).

``--scrape URL`` flips the tool into a CROSS-PROCESS dashboard: it
polls a live serving gateway's ``/metrics`` (Prometheus text, parsed
with ``observability.parse_prometheus``) and ``/healthz`` instead of
the in-process registry, and renders the same one-line dashboard —
stdlib-only (the standalone observability load), so the sidecar runs
in a bare container next to any ``examples/serve_gateway.py``.

Repeat ``--scrape`` for a FLEET dashboard over N replicas: each
target's scrape converts through
``observability.snapshot_from_prometheus`` and the round merges with
``merge_snapshots`` (fleet_obs), so the rendered tokens/s is the
exact-summed fleet counter and the latency line shows REAL fleet
p50/p95/p99 (merged fixed-bucket histograms — never averages of
per-replica quantiles), plus a quorum ``/healthz`` rollup (majority of
targets healthy = fleet healthy) and a per-rank inflight/queue strip.

Usage:
  python tools/serve_monitor.py [--dashboard-every N] [--json OUT]
  python tools/serve_monitor.py --check tools/serve_slo.json
  python tools/serve_monitor.py --scrape http://127.0.0.1:8000 \
      [--scrape-interval S] [--scrape-count N]
  python tools/serve_monitor.py --scrape http://host-a:8000 \
      --scrape http://host-b:8000 --scrape http://host-c:8000
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REPORT_SCHEMA = "paddle_tpu.serve_monitor/1"

DEFAULT_CONFIG = {
    "workload": {
        # Pareto lengths: min + scale * pareto(alpha), clamped — alpha
        # near 1 is the heavy tail (most prompts short, a few near max)
        "seed": 0, "requests": 12, "pareto_alpha": 1.1,
        "prompt_min": 4, "prompt_scale": 6, "prompt_max": 40,
        "new_tokens_mean": 5, "new_tokens_min": 2, "new_tokens_max": 8,
        # Poisson arrivals on the step clock: exponential gaps, floored
        "arrival_mean_steps": 2.0,
    },
    "engine": {
        "seed": 0, "max_seq_len": 64, "num_blocks": 40, "block_size": 8,
        "max_batch": 4, "prefill_chunk": 8, "token_budget": 16,
        # the SAMPLER runs: temperature > 0 exercises the fused
        # sampling path (step counts stay host-deterministic — the
        # schedule never depends on token VALUES)
        "temperature": 0.8, "top_p": 0.95,
    },
    "slo": {
        "cadence_s": 0.05,
        "windows": [
            {"name": "fast", "window_s": 2.0, "burn_threshold": 10.0},
            {"name": "slow", "window_s": 15.0, "burn_threshold": 2.0},
        ],
        # generous off-TPU bounds: the MECHANISM gates (breach counting,
        # window math, neutrality); the absolute numbers are interpret-
        # mode ceilings, not speed claims
        "objectives": [
            {"name": "ttft_p99", "kind": "quantile",
             "metric": "serve_ttft_seconds", "q": 0.99, "max": 60.0},
            {"name": "tpot_p99", "kind": "quantile",
             "metric": "serve_time_per_output_token_seconds",
             "q": 0.99, "max": 20.0},
            {"name": "queue_wait_p95", "kind": "quantile",
             "metric": "serve_queue_wait_seconds", "q": 0.95,
             "max": 120.0},
            {"name": "kv_alloc_failure_ratio", "kind": "ratio",
             "num": "kv_alloc_failures_total",
             "den": "serve_tokens_total", "max": 0.001},
        ],
    },
}


def build_workload(cfg, vocab):
    """Config-seeded heavy-tail request set: (prompt ids, new_tokens,
    arrival step) per request — every number a pure function of the
    seed, so the committed baseline can gate the schedule."""
    import numpy as np

    rng = np.random.default_rng(cfg["seed"])
    n = cfg["requests"]
    lens = np.clip(
        (cfg["prompt_min"]
         + cfg["prompt_scale"] * rng.pareto(cfg["pareto_alpha"], n))
        .astype(np.int64), cfg["prompt_min"], cfg["prompt_max"])
    new = np.clip(rng.poisson(cfg["new_tokens_mean"], n),
                  cfg["new_tokens_min"], cfg["new_tokens_max"])
    gaps = rng.exponential(cfg["arrival_mean_steps"], n)
    arrivals = np.floor(np.cumsum(gaps) - gaps[0]).astype(np.int64)
    prompts = [rng.integers(1, vocab, int(p)).astype(np.int32)
               for p in lens]
    return {"prompts": prompts, "new_tokens": [int(x) for x in new],
            "arrival_steps": [int(a) for a in arrivals],
            "prompt_lens": [int(x) for x in lens]}


def _drive(cb, workload, tag, max_ticks=10000):
    """Submit per the arrival schedule (step clock) and step to
    completion; returns outputs in request order + engine accounting."""
    from paddle_tpu.incubate.nn import GenerationRequest

    reqs = [GenerationRequest(p.copy(), n, request_id=f"{tag}{j}")
            for j, (p, n) in enumerate(zip(workload["prompts"],
                                           workload["new_tokens"]))]
    arrivals = workload["arrival_steps"]
    i, tick = 0, 0
    while i < len(reqs) or cb.queue or cb.num_active:
        while i < len(reqs) and arrivals[i] <= tick:
            cb.submit(reqs[i])
            i += 1
        cb.step()
        tick += 1
        if tick > max_ticks:
            raise RuntimeError(f"serve_monitor: {tag} run did not "
                               f"converge within {max_ticks} ticks")
    cb._retire()                    # flush the last step's finishers
    return {"outputs": [cb.finished[r.request_id] for r in reqs],
            "steps": cb._step_count, "ticks": tick,
            "buckets": set(cb._seen_buckets)}


def _pcts(ts, metric, window_s, now):
    out = {}
    for q in (0.5, 0.95, 0.99):
        v = ts.quantile(metric, q, window_s, now=now)
        out[f"p{int(q * 100)}"] = None if v is None else round(v * 1e3, 3)
    return out


def render_dashboard(monitor, registry, tick, out=sys.stdout):
    """One text-dashboard line + per-objective burn rates from the
    monitor's windowed rings (what a production loop would push to a
    terminal or a status page)."""
    import time as _time

    ts = monitor.timeseries
    now = _time.monotonic()
    fast = monitor.engine.windows[0]["window_s"]

    def g(name):
        s = ts.gauge_stats(name, fast, now=now)
        return "-" if s is None else f"{s['last']:g}"

    ttft = ts.quantile("serve_ttft_seconds", 0.99, fast, now=now)
    tpot = ts.quantile("serve_time_per_output_token_seconds", 0.99,
                       fast, now=now)
    rate = ts.rate("serve_tokens_total", fast, now=now)
    drops = registry.timeline_stats()["dropped"]
    print(f"[monitor step {tick:4d}] inflight {g('serve_inflight_requests')}"
          f" queue {g('serve_queue_depth')}"
          f" | kv free {g('kv_blocks_free')}"
          f" | ttft p99 {'-' if ttft is None else f'{ttft * 1e3:.0f}ms'}"
          f" tpot p99 {'-' if tpot is None else f'{tpot * 1e3:.0f}ms'}"
          f" | tok/s {'-' if rate is None else f'{rate:.1f}'}"
          f" | breaches {monitor.breaches_total}"
          + (f" | timeline drops {drops}" if drops else ""), file=out)
    rep = monitor.last_report
    if rep and rep["breaches"]:
        for o in rep["objectives"]:
            if not o["breached"]:
                continue
            for wname, ev in o["windows"].items():
                if ev and ev["breached"]:
                    print(f"  BREACH {o['name']} [{wname}]: burn "
                          f"{ev['burn_rate']:.1f}x "
                          f"(bad {ev['bad_fraction']:.2%} of "
                          f"{ev['count']})", file=out)


def _fam_sum(fams, name):
    """Sum of a family's non-histogram samples, or None when absent."""
    fam = fams.get(name)
    if not fam:
        return None
    vals = [v for n, _, v in fam["samples"] if n == name]
    return sum(vals) if vals else None


def _fam_last(fams, name):
    fam = fams.get(name)
    if not fam:
        return None
    for n, _, v in fam["samples"]:
        if n == name:
            return v
    return None


def _fam_per_label(fams, name, label):
    """{label value: sample value} for a labeled gauge family — the
    per-device view the mesh dashboard renders (empty when the scraped
    engine never exported the family, i.e. single-chip)."""
    fam = fams.get(name)
    if not fam:
        return {}
    out = {}
    for n, labels, v in fam["samples"]:
        if n == name and label in (labels or {}):
            out[labels[label]] = v
    return out


def replica_strip(fams):
    """' | replicas N [0:a 1:b]' from the router's per-replica
    inflight gauge — empty for a single-engine gateway (the family
    only exists when an EngineRouter fronts a pool)."""
    repl = _fam_per_label(fams, "router_replica_inflight", "replica")
    if not repl:
        return ""
    live = _fam_last(fams, "router_replicas_live")
    cells = " ".join(
        f"{r}:{v:g}" for r, v in sorted(repl.items(),
                                        key=lambda kv: int(kv[0])))
    n = int(live) if live is not None else len(repl)
    return f" | replicas {n}/{len(repl)} [{cells}]"


def scrape_leg(url, interval_s=2.0, count=0, out=sys.stdout):
    """Poll a live gateway's /metrics + /healthz and render the
    dashboard cross-process. `count` 0 = forever. Returns 0 once the
    poll budget is spent, 1 if every poll failed."""
    import time
    import urllib.error
    import urllib.request

    from tools.metrics_snapshot import _load_observability

    obs = _load_observability()
    base = url.rstrip("/")
    if base.endswith("/metrics"):
        base = base[: -len("/metrics")]
    prev_tokens = prev_t = None
    polls = ok_polls = 0
    while count == 0 or polls < count:
        if polls:
            time.sleep(interval_s)
        polls += 1
        try:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=5) as r:
                fams = obs.parse_prometheus(r.read().decode())
        except (OSError, ValueError) as e:
            print(f"[scrape {polls}] {base}/metrics unreachable: {e}",
                  file=out)
            continue
        ok_polls += 1
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=5) as r:
                health = f"ok({r.status})"
        except urllib.error.HTTPError as e:
            health = f"degraded({e.code})"
        except OSError:
            health = "unreachable"
        now = time.monotonic()
        tokens = _fam_sum(fams, "serve_tokens_total")
        rate = None
        if tokens is not None and prev_tokens is not None \
                and now > prev_t:
            rate = (tokens - prev_tokens) / (now - prev_t)
        prev_tokens, prev_t = tokens, now

        def g(name):
            v = _fam_last(fams, name)
            return "-" if v is None else f"{v:g}"

        breaches = _fam_sum(fams, "slo_breaches_total")
        # mesh-aware view: a TP engine exports per-device KV/HBM
        # gauges — render every device's shard, not a silent device-0
        # aggregate (single-chip gateways simply lack the family)
        dev_kv = _fam_per_label(fams, "kv_device_bytes_used", "device")
        tp_w = _fam_last(fams, "serve_tp_degree")
        mesh = ""
        if dev_kv:
            cells = " ".join(
                f"{d}:{int(v) // 1024}K"
                for d, v in sorted(dev_kv.items(),
                                   key=lambda kv: int(kv[0])))
            mesh = (f" | tp {int(tp_w) if tp_w else len(dev_kv)}"
                    f" kv/dev [{cells}]")
        print(f"[scrape {polls:3d}] health {health}"
              f" | inflight {g('serve_inflight_requests')}"
              f" queue {g('serve_queue_depth')}"
              f" | kv free {g('kv_blocks_free')}{mesh}"
              f"{replica_strip(fams)}"
              f" | conns {g('gateway_live_connections')}"
              f" streams {g('gateway_live_streams')}"
              f" sse-pending {g('gateway_sse_pending_events')}"
              f" | tokens {int(tokens) if tokens is not None else '-'}"
              f" ({'-' if rate is None else f'{rate:.1f}/s'})"
              f" | breaches {int(breaches) if breaches is not None else 0}",
              file=out)
    return 0 if ok_polls else 1


def _merged_counter(view, name):
    fam = view["metrics"].get(name)
    if not fam or fam.get("kind") != "counter":
        return None
    vals = [c["value"] for c in fam["children"].values()]
    return sum(vals) if vals else None


def _rank_gauge_strip(view, name):
    """'r0:3 r1:5 ...' from a merged gauge's appended rank label."""
    fam = view["metrics"].get(name)
    if not fam or fam.get("kind") != "gauge":
        return ""
    cells = {}
    for ckey, child in fam["children"].items():
        rank = ckey.rsplit(",", 1)[-1] if ckey else ckey
        cells[rank] = cells.get(rank, 0.0) + child["value"]
    return " ".join(f"r{r}:{v:g}" for r, v in
                    sorted(cells.items(), key=lambda kv: kv[0]))


def scrape_fleet(urls, interval_s=2.0, count=0, out=sys.stdout):
    """Poll N live gateways and render the AGGREGATED dashboard: each
    round's scrapes convert through snapshot_from_prometheus and merge
    with merge_snapshots, so tokens/s is the exact fleet counter sum,
    the latency cells are real merged-histogram quantiles, and health
    is a quorum rollup over the targets' /healthz answers. A partially
    reachable fleet still renders (the view covers the ranks that
    answered); a round where NO target answers counts as failed."""
    import time
    import urllib.error
    import urllib.request

    from tools.metrics_snapshot import _load_observability

    obs = _load_observability()
    bases = []
    for u in urls:
        base = u.rstrip("/")
        if base.endswith("/metrics"):
            base = base[: -len("/metrics")]
        bases.append(base)
    world = len(bases)
    quorum = world // 2 + 1
    prev_tokens = prev_t = None
    polls = ok_polls = 0
    while count == 0 or polls < count:
        if polls:
            time.sleep(interval_s)
        polls += 1
        snaps, health = {}, {}
        for rank, base in enumerate(bases):
            try:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=5) as r:
                    snaps[rank] = {
                        "rank": rank, "world_size": world,
                        "metrics": obs.snapshot_from_prometheus(
                            r.read().decode())}
            except (OSError, ValueError) as e:
                health[rank] = "unreachable"
                print(f"[fleet {polls}] r{rank} {base}/metrics "
                      f"unreachable: {e}", file=out)
                continue
            try:
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=5) as r:
                    health[rank] = "ok"
            except urllib.error.HTTPError:
                health[rank] = "degraded"
            except OSError:
                health[rank] = "unreachable"
        if not snaps:
            continue
        ok_polls += 1
        view = obs.merge_snapshots(snaps)
        n_ok = sum(1 for h in health.values() if h == "ok")
        rollup = "ok" if n_ok >= quorum else \
            ("degraded" if n_ok else "down")
        now = time.monotonic()
        tokens = _merged_counter(view, "serve_tokens_total")
        rate = None
        if tokens is not None and prev_tokens is not None \
                and now > prev_t:
            rate = (tokens - prev_tokens) / (now - prev_t)
        prev_tokens, prev_t = tokens, now

        def pcts(name):
            cells = []
            for q in (0.5, 0.95, 0.99):
                try:
                    v = obs.merged_quantile(view, name, q)
                except (KeyError, ValueError):
                    v = None
                cells.append("-" if v is None else f"{v * 1e3:.0f}")
            return "/".join(cells)

        breaches = _merged_counter(view, "slo_breaches_total")
        print(f"[fleet {polls:3d}] quorum {rollup} ({n_ok}/{world} ok,"
              f" {len(snaps)} scraped)"
              f" | ttft p50/95/99 {pcts('serve_ttft_seconds')}ms"
              f" tpot {pcts('serve_tpot_seconds')}ms"
              f" | inflight [{_rank_gauge_strip(view, 'serve_inflight_requests')}]"
              f" queue [{_rank_gauge_strip(view, 'serve_queue_depth')}]"
              f" | tokens {int(tokens) if tokens is not None else '-'}"
              f" ({'-' if rate is None else f'{rate:.1f}/s'})"
              f" | breaches {int(breaches) if breaches is not None else 0}",
              file=out)
    return 0 if ok_polls else 1


def monitor_leg(config=None, dashboard_every=0):
    """The full leg: warmup run -> monitored run (SLO engine attached)
    -> unmonitored run; neutrality + bucket accounting + windowed
    percentiles + the final SLO report."""
    import time as _time

    import jax

    from paddle_tpu import observability as obs
    from paddle_tpu.incubate.nn import ContinuousBatchingEngine
    from paddle_tpu.ops.pallas import flash_attention as fa
    from tools.serve_bench import _tiny_cpu_engine

    import numpy as np

    config = config or DEFAULT_CONFIG
    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        fa._INTERPRET = True
    ecfg = config["engine"]
    rng = np.random.default_rng(ecfg["seed"])
    eng, V = _tiny_cpu_engine(rng, max_seq_len=ecfg["max_seq_len"])
    workload = build_workload(config["workload"], V)

    def make_cb(monitor=None):
        return ContinuousBatchingEngine(
            eng, num_blocks=ecfg["num_blocks"],
            block_size=ecfg["block_size"], max_batch=ecfg["max_batch"],
            prefill_chunk=ecfg["prefill_chunk"],
            token_budget=ecfg["token_budget"],
            temperature=ecfg["temperature"], top_p=ecfg["top_p"],
            monitor=monitor)

    warm = _drive(make_cb(), workload, "mw")

    monitor = obs.SLOMonitor.from_config(config["slo"])
    reg = obs.get_registry()
    t0 = _time.monotonic()
    if dashboard_every:
        # wrap the monitor's tick to interleave dashboard rendering the
        # way a server's status loop would
        cb_mon = make_cb(monitor)
        orig_step, ticks = cb_mon.step, [0]

        def step_with_dash():
            r = orig_step()
            ticks[0] += 1
            if ticks[0] % dashboard_every == 0:
                render_dashboard(monitor, reg, ticks[0])
            return r

        cb_mon.step = step_with_dash
        monitored = _drive(cb_mon, workload, "mm")
    else:
        monitored = _drive(make_cb(monitor), workload, "mm")
    elapsed = _time.monotonic() - t0
    final = monitor.force()         # end-of-run sample + evaluation

    # windowed percentiles NOW, while `now` still sits at the monitored
    # run's end: the plain leg below takes about as long as the
    # monitored one, and a later `now` would drift the window
    # [now - W, now] past the newest sample — the p99 gate would read
    # an empty window ("no data") instead of the run it claims to gate
    now = _time.monotonic()
    full_window = elapsed + 2 * monitor.cadence_s + 1.0
    ts = monitor.timeseries
    windowed = {
        "window_s": round(full_window, 3),
        "ttft_ms": _pcts(ts, "serve_ttft_seconds", full_window, now),
        "tpot_ms": _pcts(ts, "serve_time_per_output_token_seconds",
                         full_window, now),
        "queue_wait_ms": _pcts(ts, "serve_queue_wait_seconds",
                               full_window, now),
    }

    plain = _drive(make_cb(), workload, "mp")

    out = {
        "schema": REPORT_SCHEMA,
        "interpret": not on_tpu,
        "config": {k: config[k] for k in ("workload", "engine", "slo")},
        "workload": {
            "requests": len(workload["prompts"]),
            "prompt_lens": workload["prompt_lens"],
            "new_tokens": workload["new_tokens"],
            "arrival_steps": workload["arrival_steps"],
            "total_prompt_tokens": sum(workload["prompt_lens"]),
            "total_new_tokens": sum(workload["new_tokens"]),
        },
        "steps_warmup": warm["steps"],
        "steps_monitored": monitored["steps"],
        "steps_plain": plain["steps"],
        "tokens_generated": sum(len(o) for o in monitored["outputs"]),
        "token_exact_monitor_on_off":
            monitored["outputs"] == plain["outputs"],
        "new_buckets_after_warmup": len(
            (monitored["buckets"] | plain["buckets"]) - warm["buckets"]),
        "monitor": {
            "ticks": monitored["ticks"] + 1,    # + the final force()
            "evaluations": monitor.engine.evaluations,
            "samples": ts.samples_taken,
            "cadence_s": monitor.cadence_s,
        },
        "windowed": windowed,
        "breaches": monitor.breaches_total,
        "breach_counts": {f"{o}/{w}": n for (o, w), n
                          in monitor.engine.breach_counts.items()},
        # json_safe: an infinite burn rate (zero-budget ratio breach)
        # must not land as a bare `Infinity` literal in the report file
        "slo_report": obs.json_safe(final),
        "timeline_dropped": reg.timeline_stats()["dropped"],
    }
    fr = obs.get_flight_recorder()
    out["flight"] = {"armed": fr.armed, "retained": len(fr.retained()),
                     "dumps_this_process": len(fr.dumps)}
    print(f"monitor leg: {out['steps_monitored']} steps monitored vs "
          f"{out['steps_plain']} plain ({out['tokens_generated']} tokens,"
          f" {out['monitor']['evaluations']} SLO evaluations), "
          f"{out['breaches']} breaches, "
          f"{out['new_buckets_after_warmup']} new buckets after warmup; "
          f"windowed ttft p99 {out['windowed']['ttft_ms']['p99']} ms, "
          f"tpot p99 {out['windowed']['tpot_ms']['p99']} ms"
          + (" [interpret: latencies time the interpreter, not the "
             "chip]" if not on_tpu else ""))
    return out


# host-deterministic keys: must match the committed baseline exactly
MONITOR_KEYS = ("workload", "steps_warmup", "steps_monitored",
                "steps_plain", "tokens_generated",
                "token_exact_monitor_on_off", "new_buckets_after_warmup",
                "breaches")


def _objective_max(config, metric):
    for o in config["slo"]["objectives"]:
        if o.get("metric") == metric:
            return o["max"]
    return None


def check_monitor(base):
    """CI gate: deterministic accounting against the committed
    baseline, monitor neutrality, zero breaches, zero new buckets, and
    windowed p99 TTFT/TPOT under the declared objectives."""
    cur = monitor_leg(config=base.get("config") or DEFAULT_CONFIG)
    bad = [k for k in MONITOR_KEYS if cur[k] != base[k]]
    for k in bad:
        print(f"MISMATCH {k}: current {cur[k]!r} != baseline {base[k]!r}")
    if not cur["token_exact_monitor_on_off"]:
        print("REGRESSION: attaching the SLO monitor changed generated "
              "tokens")
        bad.append("token_exact_monitor_on_off")
    if cur["steps_monitored"] != cur["steps_plain"]:
        print(f"REGRESSION: monitoring changed the step count "
              f"({cur['steps_monitored']} vs {cur['steps_plain']})")
        bad.append("steps_monitored")
    if cur["new_buckets_after_warmup"] != 0:
        print(f"REGRESSION: the monitored run compiled "
              f"{cur['new_buckets_after_warmup']} fresh buckets after "
              "warmup")
        bad.append("new_buckets_after_warmup")
    if cur["breaches"] != 0:
        print(f"REGRESSION: {cur['breaches']} SLO burn-rate breaches on "
              f"the healthy heavy-tail workload: {cur['breach_counts']}")
        bad.append("breaches")
    cfg = base.get("config") or DEFAULT_CONFIG
    for label, metric in (("ttft_ms", "serve_ttft_seconds"),
                          ("tpot_ms",
                           "serve_time_per_output_token_seconds")):
        p99 = cur["windowed"][label]["p99"]
        limit = _objective_max(cfg, metric)
        if p99 is None:
            print(f"REGRESSION: windowed {label} p99 has no data")
            bad.append(label)
        elif limit is not None and p99 / 1e3 >= limit:
            print(f"REGRESSION: windowed {label} p99 {p99} ms breaches "
                  f"the declared objective ({limit * 1e3:g} ms)")
            bad.append(label)
    # the report embedded in the run must satisfy its own schema
    from paddle_tpu.observability import validate_report
    try:
        validate_report(cur["slo_report"])
    except ValueError as e:
        print(f"REGRESSION: SLO report schema violation: {e}")
        bad.append("slo_report")
    if bad:
        return 1
    print(f"monitor leg OK: {cur['steps_monitored']} steps (monitor on "
          f"== off), token-exact, 0 breaches / "
          f"{cur['monitor']['evaluations']} evaluations, 0 new buckets, "
          f"windowed p99 under objectives")
    return 0


def main():
    ap = argparse.ArgumentParser(
        description="heavy-tail serving load + windowed SLO monitoring")
    ap.add_argument("--json", default=None,
                    help="write the full JSON report here")
    ap.add_argument("--check", metavar="BASELINE_JSON", default=None,
                    help="gate against a committed baseline "
                         "(tools/serve_slo.json)")
    ap.add_argument("--dashboard-every", type=int, default=10,
                    help="render the text dashboard every N engine "
                         "steps (0 disables)")
    ap.add_argument("--no-flight-recorder", action="store_true",
                    help="do not arm the flight recorder (armed by "
                         "default with bounded retention — the "
                         "server-entrypoint policy)")
    ap.add_argument("--scrape", metavar="URL", action="append",
                    default=None,
                    help="poll a live gateway's /metrics + /healthz "
                         "instead of driving an in-process engine "
                         "(cross-process dashboard; stdlib-only). "
                         "Repeat for a FLEET: N targets merge into one "
                         "aggregated dashboard with real fleet "
                         "quantiles and a quorum /healthz rollup")
    ap.add_argument("--scrape-interval", type=float, default=2.0,
                    help="seconds between scrape polls")
    ap.add_argument("--scrape-count", type=int, default=0,
                    help="number of polls (0 = forever)")
    args = ap.parse_args()

    if args.scrape:
        # a sidecar scraper neither serves nor dumps: no engine, no
        # flight recorder, no jax
        if len(args.scrape) > 1:
            return scrape_fleet(args.scrape, args.scrape_interval,
                                args.scrape_count)
        return scrape_leg(args.scrape[0], args.scrape_interval,
                          args.scrape_count)

    from paddle_tpu.observability import tracing
    if not args.no_flight_recorder:
        fr = tracing.arm_default()
        print(f"flight recorder armed: {fr._dir} "
              f"(max_dumps={fr.max_dumps}, max_bytes={fr.max_bytes})")

    if args.check:
        with open(args.check) as f:
            base = json.load(f)
        if "monitor" not in base:
            print(f"{args.check}: no 'monitor' section to gate")
            return 1
        return check_monitor(base["monitor"])

    out = monitor_leg(dashboard_every=args.dashboard_every)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    # operator abort mid-run writes the operator_abort flight dump
    # (span window + full metrics snapshot) before exiting — a monitor
    # killed mid-incident must not take its evidence along
    from paddle_tpu.observability import tracing
    sys.exit(tracing.run_with_abort_evidence(main))
