"""Serving: fused-transformer decode engine with the whole generation loop
compiled as ONE program (prefill + lax.scan decode, donated caches).

Run: python examples/serve_llama.py [--quant int8|int4] [--continuous]
Weight-only quantization halves (int8) or quarters (int4) the decoder
weight HBM — the dequant fuses into the MXU matmul.

--continuous switches to the continuous-batching path: requests of
unequal prompt/output lengths share one paged KV cache through a
host-side block allocator, and every step runs the whole mixed-progress
batch as one compiled program over the ragged paged-attention kernel."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import argparse
import time

import numpy as np

from paddle_tpu.inference import FusedMultiTransformerEngine


def run_continuous(engine, rng, V, args):
    from paddle_tpu.incubate.nn import (ContinuousBatchingEngine,
                                        GenerationRequest)
    if not args.no_flight_recorder:
        # server-style entrypoints arm by default with bounded
        # retention: an anomaly mid-serve leaves evidence without a
        # human having opted in first (disable with --no-flight-recorder)
        from paddle_tpu.observability import tracing
        fr = tracing.arm_default(args.flight_dir)
        print(f"flight recorder armed: {fr._dir} "
              f"(max_dumps={fr.max_dumps}, replay dumps with "
              "tools/request_trace.py)")
    cb = ContinuousBatchingEngine(engine, num_blocks=33, block_size=16,
                                  max_batch=args.batch,
                                  prefill_chunk=args.prefill_chunk,
                                  token_budget=args.token_budget,
                                  spec_k=args.spec_k,
                                  prefix_cache=args.prefix_cache)
    free0 = cb.allocator.num_free
    lengths = [(5, 12), (23, 8), (3, 30), (17, 17), (9, 5), (40, 11)]
    if args.prefix_cache:
        # shared system preamble: every request repeats the same
        # 48-token prefix — only the FIRST prefills it; the rest map
        # the cached blocks straight into their block tables
        preamble = rng.integers(1, V, 48).astype(np.int32)
        prompts = [np.concatenate([preamble,
                                   rng.integers(1, V, p).astype(np.int32)])
                   for p, _ in lengths]
        lengths = [(len(pr), n) for pr, (_, n) in zip(prompts, lengths)]
    else:
        prompts = [rng.integers(1, V, p).astype(np.int32)
                   for p, _ in lengths]
    reqs = [GenerationRequest(pr, n) for pr, (_, n) in zip(prompts, lengths)]
    for r in reqs:
        cb.submit(r)
    t0 = time.perf_counter()
    out = cb.run()
    dt = time.perf_counter() - t0
    tok = sum(len(v) for v in out.values())
    print(f"continuous batching: {len(reqs)} ragged requests "
          f"(prompts {[p for p, _ in lengths]}) -> {tok} tokens in "
          f"{cb._step_count} steps, {dt * 1000:.1f} ms; "
          f"free blocks {cb.allocator.num_free}"
          + (f" + {cb.allocator.num_pooled} pooled" if args.prefix_cache
             else "")
          + f"/{free0}")
    drafted = sum(r.spec_drafted for r in reqs)
    if drafted:
        print(f"  speculative: {sum(r.spec_accepted for r in reqs)}"
              f"/{drafted} drafts accepted")
    if cb.tp > 1:
        from paddle_tpu import observability as obs
        rows = cb.device_kv_report()
        comm = obs.get_registry().get("collective_bytes_total")
        total = sum(c.value for c in comm._children.values()) \
            if comm is not None else 0
        print(f"  tensor parallel: tp={cb.tp}, per-device KV high-water "
              f"{rows[0]['kv_bytes_high_water']} B (1/{cb.tp} of "
              f"single-chip), collective payload {int(total)} B "
              f"(psum over 'tp')")
    if args.prefix_cache:
        cached = {r.request_id: cb.explain(r.request_id)
                  ["cached_prefix_tokens"] for r in reqs}
        print(f"  prefix cache: reused tokens per request {cached} "
              f"(shared blocks skip their prefill chunks entirely)")
    for r, (p, n) in zip(reqs, lengths):
        print(f"  req {r.request_id} (prompt {p:2d}, max_new {n:2d}): "
              f"{out[r.request_id][:8]}")
    if args.trace:
        from paddle_tpu.observability import tracing
        path = tracing.write_dump(args.trace, reason="serve_llama",
                                  requests=len(reqs))
        print(f"  trace dump -> {path} "
              "(replay: python tools/request_trace.py " + args.trace + ")")
        for r in reqs:
            ex = cb.explain(r.request_id)
            print(f"  req {r.request_id}: queue_wait "
                  f"{ex['queue_wait_s'] * 1e3:.2f} ms, ttft "
                  f"{ex['ttft_s'] * 1e3:.1f} ms, "
                  f"{len(ex['prefill_chunks'])} prefill chunks, "
                  f"{ex['decode_steps']} decode steps, "
                  f"stalls {sum(ex['stalls'].values())}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quant", choices=["none", "int8", "int4"],
                    default="none")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--continuous", action="store_true",
                    help="continuous-batching serving over the paged "
                         "cache (ragged Pallas kernel)")
    ap.add_argument("--prefill-chunk", type=int, default=64,
                    help="prompt tokens consumed per slot per step "
                         "(1 = the old one-token-per-step prefill)")
    ap.add_argument("--token-budget", type=int, default=None,
                    help="per-step token budget shared by decode slots "
                         "(1 token each, mandatory) and prompt chunks")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decode: up to K prompt-lookup "
                         "draft tokens per decode slot per step "
                         "(greedy only; 0 disables)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="(--continuous only) content-addressed sharing "
                         "of full paged-KV blocks across requests: "
                         "repeated prompt prefixes map cached blocks "
                         "instead of re-prefilling (copy-on-write on "
                         "divergence)")
    ap.add_argument("--trace", metavar="OUT.json", default=None,
                    help="(--continuous only) dump per-request lifecycle "
                         "spans + metrics after the run; replay with "
                         "tools/request_trace.py")
    ap.add_argument("--flight-dir", default=None,
                    help="(--continuous only) flight-recorder dump dir "
                         "(default: $PADDLE_TPU_FLIGHT_DIR or the "
                         "system tmpdir; retention keeps it bounded)")
    ap.add_argument("--no-flight-recorder", action="store_true",
                    help="(--continuous only) do not arm the anomaly "
                         "flight recorder (armed by default with "
                         "bounded retention)")
    ap.add_argument("--tp", type=int, default=1,
                    help="(--continuous only) tensor-parallel width: "
                         "shard the paged serving path over a tp-device "
                         "mesh (kv-head-sharded cache + work-list "
                         "kernel, Megatron column/row weight split, one "
                         "scheduler brain on the host). Off-TPU the "
                         "mesh is virtual CPU devices. Requires heads/"
                         "kv-heads/FFN divisible by tp (here: tp in "
                         "{1, 2, 4})")
    args = ap.parse_args()
    if args.tp > 1:
        if not args.continuous:
            ap.error("--tp needs --continuous (the paged serving path "
                     "is the sharded one; dense generate() is "
                     "single-chip)")
        # must land before the first jax backend init: off-TPU the tp
        # mesh runs on virtual CPU devices (the dryrun_multichip
        # pattern)
        import os
        flag = f"--xla_force_host_platform_device_count={args.tp}"
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag).strip()

    rng = np.random.default_rng(0)
    V, E, H, G, D, L, F = 512, 128, 8, 4, 16, 4, 344
    SMAX = 128

    def mk(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    weights = dict(
        ln_scales=[np.ones(E, np.float32) for _ in range(L)],
        qkv_weights=[mk(H + 2 * G, D, E) for _ in range(L)],
        linear_weights=[mk(H * D, E) for _ in range(L)],
        ffn_ln_scales=[np.ones(E, np.float32) for _ in range(L)],
        ffn1_weights=[mk(E, 2 * F) for _ in range(L)],
        ffn2_weights=[mk(F, E) for _ in range(L)],
        embedding=mk(V, E), lm_head=mk(E, V))

    engine = FusedMultiTransformerEngine(
        weights, num_heads=H, head_dim=D, max_seq_len=SMAX,
        dtype="float32", norm_type="rmsnorm", activation="swiglu",
        gqa_group_size=G,
        weight_quant=None if args.quant == "none" else args.quant,
        tp=args.tp)

    if args.continuous:
        import jax
        if jax.devices()[0].platform != "tpu":
            from paddle_tpu.ops.pallas import flash_attention as _fa
            _fa._INTERPRET = True  # run the Pallas kernels on CPU
        return run_continuous(engine, rng, V, args)

    prompts = rng.integers(0, V, (args.batch, 16)).astype(np.int32)
    engine.generate(prompts, max_new_tokens=args.new_tokens)  # compile
    t0 = time.perf_counter()
    out = engine.generate(prompts, max_new_tokens=args.new_tokens,
                          temperature=0.8, top_p=0.95, seed=7)
    dt = time.perf_counter() - t0
    print(f"quant={args.quant}: generated {out.shape} in {dt * 1000:.1f} ms "
          f"({args.batch * args.new_tokens / dt:.0f} tok/s)")
    print("sampled ids[0]:", out[0][:16].tolist())


if __name__ == "__main__":
    # operator abort (Ctrl-C / sys.exit mid-serve) leaves evidence
    # instead of dying mid-step with none: the shared wrapper writes an
    # operator_abort flight dump (span window + full metrics snapshot)
    from paddle_tpu.observability import tracing
    sys.exit(tracing.run_with_abort_evidence(main))
