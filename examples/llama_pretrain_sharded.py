"""Sharded Llama pretraining over a device mesh (the headline path).

Run (single chip or CPU):      python examples/llama_pretrain_sharded.py
Run (8 virtual CPU devices):
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/llama_pretrain_sharded.py --dp 2 --fsdp 2 --mp 2

The mesh axes are the parallelism plan: dp shards the batch, fsdp shards
params + optimizer moments (ZeRO-3 at rest), mp is tensor parallelism,
sp sequence/context parallelism. GSPMD inserts every collective."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import argparse

import numpy as np

from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, pretrain


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--metrics", action="store_true",
                    help="count XLA compiles + step time/tokens-per-s "
                         "and print the metrics snapshot at the end")
    ap.add_argument("--health", action="store_true",
                    help="training health monitoring: per-layer-group "
                         "gradient telemetry + divergence detection "
                         "(TrainHealthMonitor), step-phase breakdown, "
                         "and the arm-by-default flight recorder — a "
                         "NaN'd loss or a starved pipeline leaves a "
                         "dump instead of a ruined run")
    args = ap.parse_args()

    monitor = None
    if args.health:
        from paddle_tpu import observability as obs
        from paddle_tpu.observability import tracing
        # serve entrypoints arm by default (PR 8); with --health the
        # pretrain example does too: breach dumps land in
        # $PADDLE_TPU_FLIGHT_DIR (or the tmp default) under retention
        tracing.arm_default()
        monitor = obs.TrainHealthMonitor()

    if args.metrics:
        from paddle_tpu import observability as obs
        obs.install_compile_watch()

    cfg = LlamaConfig(
        vocab_size=2048, hidden_size=256, intermediate_size=688,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=args.seq, dtype="float32")
    model = LlamaForCausalLM(cfg)

    n_dev = args.dp * args.fsdp * args.mp * args.sp
    mesh = pretrain.make_mesh(n_dev, dp=args.dp, fsdp=args.fsdp,
                              mp=args.mp, sp=args.sp)
    params, opt_state, meta = pretrain.make_train_state(model, mesh)
    step = pretrain.make_train_step(model, mesh, meta, monitor=monitor)

    rng = np.random.default_rng(0)

    def gen_batches():
        for _ in range(args.steps):
            yield {"input_ids": rng.integers(
                       0, cfg.vocab_size,
                       (args.batch, args.seq)).astype(np.int32),
                   "labels": rng.integers(
                       0, cfg.vocab_size,
                       (args.batch, args.seq)).astype(np.int32)}

    batches = gen_batches()
    if monitor is not None:
        # data-pipeline telemetry: per-batch wait + stall detection on
        # the same monitor (a real run would set instrument=True on
        # its DataLoader instead)
        from paddle_tpu.observability import train_health
        batches = train_health.instrument_loader(batches,
                                                 monitor=monitor)
    for i, host_batch in enumerate(batches):
        batch = pretrain.shard_batch(host_batch, mesh)
        params, opt_state, loss, gnorm = step(params, opt_state, batch)
        print(f"step {i}: loss {float(loss):.4f} gnorm {float(gnorm):.3f}")

    if monitor is not None:
        rep = monitor.report()
        print(f"train health: {rep['breaches_total']} breaches over "
              f"{rep['steps_observed']} monitored steps "
              f"({rep['breach_counts'] or 'all checks quiet'})")
        from paddle_tpu import observability as obs
        snap = obs.get_registry().snapshot()
        groups = snap.get("train_group_grad_norm", {}).get("children",
                                                           {})
        ratios = snap.get("train_group_update_ratio",
                          {}).get("children", {})
        for label in groups:
            print(f"  {label:>14}: grad_norm "
                  f"{groups[label]['value']:.4f}  upd/param "
                  f"{ratios.get(label, {}).get('value', 0):.2e}")

    if args.metrics:
        reg = obs.get_registry()
        snap = reg.snapshot().get("jax_compiles_total", {})
        backend = sum(
            c["value"] for name, c in snap.get("children", {}).items()
            if name.startswith("backend_compile"))
        print(f"backend compiles: {backend:.0f}")
        steps_h = reg.get("train_step_seconds")
        if steps_h is not None and steps_h.count:
            print(f"step p50 {steps_h.quantile(0.5)*1e3:.1f} ms, "
                  f"p95 {steps_h.quantile(0.95)*1e3:.1f} ms over "
                  f"{steps_h.count} steps")
        print(obs.to_json(indent=1))


if __name__ == "__main__":
    main()
