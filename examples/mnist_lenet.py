"""LeNet on MNIST — the minimum end-to-end slice (BASELINE config 1).

Run: python examples/mnist_lenet.py
Uses the local MNIST cache when present, a deterministic synthetic
stand-in otherwise (zero-egress environments)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def main():
    train_ds = MNIST(mode="train")
    loader = DataLoader(train_ds, batch_size=128, shuffle=True)
    model = LeNet(num_classes=10)
    opt = optimizer.Adam(parameters=model.parameters(), learning_rate=1e-3)

    model.train()
    for epoch in range(2):
        seen = correct = 0
        for i, (img, label) in enumerate(loader):
            img = paddle.to_tensor(np.asarray(img, np.float32))
            label = paddle.to_tensor(np.asarray(label, np.int64))
            logits = model(img.reshape([-1, 1, 28, 28]))
            loss = nn.functional.cross_entropy(logits, label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            pred = np.asarray(logits.numpy()).argmax(-1)
            correct += int((pred == np.asarray(label.numpy())).sum())
            seen += len(pred)
            if i % 50 == 0:
                print(f"epoch {epoch} step {i}: loss "
                      f"{float(loss.numpy()):.4f} acc {correct / seen:.3f}")
            if i >= 150:
                break
    print(f"final train accuracy: {correct / seen:.3f}")


if __name__ == "__main__":
    main()
