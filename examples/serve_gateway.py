#!/usr/bin/env python
"""Serve a llama-style model over the HTTP/SSE gateway (ISSUE 12).

The production-front-door entrypoint: the continuous-batching engine
(paged ragged attention, chunked prefill, speculative decode, prefix
caching, priority/deadline resilience) on a dedicated stepper thread,
fronted by the asyncio gateway — per-token SSE streaming, mid-stream
cancellation, and the live observability control plane (/metrics,
/slo, /requests, /dumps, /healthz).

Same operational posture as serve_llama/serve_bench/serve_monitor:
the flight recorder is armed by default with bounded retention, and
Ctrl-C (or a mid-run sys.exit) leaves an `operator_abort` flight dump
carrying the span window + a final metrics snapshot.

Try it:
  python examples/serve_gateway.py --port 8000 &
  curl -N -X POST localhost:8000/v1/generate \
    -d '{"prompt": [11, 7, 19], "max_new_tokens": 8}'
  curl localhost:8000/metrics | head
  curl localhost:8000/healthz
  python tools/serve_monitor.py --scrape http://localhost:8000
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from paddle_tpu.inference import FusedMultiTransformerEngine  # noqa: E402


def main():
    ap = argparse.ArgumentParser(
        description="HTTP/SSE serving gateway over the "
                    "continuous-batching engine")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--num-blocks", type=int, default=64,
                    help="paged-KV pool size (blocks)")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=64)
    ap.add_argument("--token-budget", type=int, default=None)
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft length (greedy only)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="content-addressed COW prefix sharing")
    ap.add_argument("--shed-on-pressure", action="store_true",
                    help="shed low-priority queued work on SLO burn / "
                         "HBM pressure")
    ap.add_argument("--no-flight-recorder", action="store_true",
                    help="do not arm the anomaly flight recorder "
                         "(armed by default with bounded retention)")
    ap.add_argument("--flight-dir", default=None)
    args = ap.parse_args()

    import jax

    from paddle_tpu.incubate.nn import ContinuousBatchingEngine
    from paddle_tpu.observability import SLOMonitor, tracing
    from paddle_tpu.serving import run_gateway

    if jax.devices()[0].platform != "tpu":
        from paddle_tpu.ops.pallas import flash_attention as _fa
        _fa._INTERPRET = True   # run the Pallas kernels on CPU

    if not args.no_flight_recorder:
        fr = tracing.arm_default(args.flight_dir)
        print(f"flight recorder armed: {fr._dir} "
              f"(max_dumps={fr.max_dumps}, max_bytes={fr.max_bytes})")

    # the serve_llama demo model: random weights, llama-shaped config
    rng = np.random.default_rng(0)
    V, E, H, G, D, L, F = 512, 128, 8, 4, 16, 4, 344
    SMAX = 128

    def mk(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    weights = dict(
        ln_scales=[np.ones(E, np.float32) for _ in range(L)],
        qkv_weights=[mk(H + 2 * G, D, E) for _ in range(L)],
        linear_weights=[mk(H * D, E) for _ in range(L)],
        ffn_ln_scales=[np.ones(E, np.float32) for _ in range(L)],
        ffn1_weights=[mk(E, 2 * F) for _ in range(L)],
        ffn2_weights=[mk(F, E) for _ in range(L)],
        embedding=mk(V, E), lm_head=mk(E, V))
    engine = FusedMultiTransformerEngine(
        weights, num_heads=H, head_dim=D, max_seq_len=SMAX,
        dtype="float32", norm_type="rmsnorm", activation="swiglu",
        gqa_group_size=G)

    monitor = SLOMonitor.from_config({
        "cadence_s": 1.0,
        "objectives": [
            {"name": "ttft_p99", "kind": "quantile",
             "metric": "serve_ttft_seconds", "q": 0.99, "max": 60.0},
            {"name": "kv_alloc_failure_ratio", "kind": "ratio",
             "num": "kv_alloc_failures_total",
             "den": "serve_tokens_total", "max": 0.001},
        ]})
    cb = ContinuousBatchingEngine(
        engine, num_blocks=args.num_blocks, block_size=args.block_size,
        max_batch=args.max_batch, prefill_chunk=args.prefill_chunk,
        token_budget=args.token_budget, spec_k=args.spec_k,
        prefix_cache=args.prefix_cache, monitor=monitor,
        shed_on_pressure=args.shed_on_pressure)
    print(f"engine up: vocab {V}, {L} layers, {args.num_blocks} KV "
          f"blocks x {args.block_size}, max_batch {args.max_batch}")
    return run_gateway(cb, host=args.host, port=args.port,
                       monitor=monitor)


if __name__ == "__main__":
    # operator abort (Ctrl-C / sys.exit mid-serve) leaves evidence: the
    # shared wrapper writes an operator_abort flight dump (span window
    # + full metrics snapshot) before exiting 130
    from paddle_tpu.observability import tracing
    sys.exit(tracing.run_with_abort_evidence(main))
